"""MD: declared-vs-documented metric-family cross-check.

The static generalization of PR 11's runtime ``/metricsz`` lint
(tests/test_attrib.py): that test asserts what one live gateway
*exports*; this checker asserts, at lint time and over the whole tree,
that the three representations of the metric plane agree:

  * **manifest** — ``FAMILIES`` in obs/prom.py, the declared name→type
    table every family must be registered in;
  * **code** — family names the source actually constructs: live
    histogram names (``.observe("<name>", ...)`` → ``llmc_<name>_seconds``)
    and the ``gauges``/``families`` tables assembled in
    ``ConsensusGateway.metricsz`` / ``ChipTimeLedger.prom_families``;
  * **docs** — the family tables in docs/observability.md.

Findings:
  MD01 — a family constructed in code that the manifest doesn't declare
  MD02 — a manifest family missing from docs/observability.md
  MD03 — a docs family the manifest doesn't declare (stale/typo'd row)
  MD04 — the ``FAMILIES`` manifest could not be parsed

Label-dict keys (``family``/``disposition``/...) and the families-entry
shape keys (``type``/``samples``) are excluded from code collection by
name — the collection walks only functions named ``metricsz`` /
``prom_families``, so the exclusion list stays small and local.
"""

from __future__ import annotations

import ast
import re

from llm_consensus_tpu.analysis.core import Finding, Project, checker

PROM_PATH = "llm_consensus_tpu/obs/prom.py"
DOC_PATH = "docs/observability.md"
_DOC_TOKEN_RE = re.compile(r"llmc_[a-z0-9_]*[a-z0-9]")
_FAMILY_FNS = ("metricsz", "prom_families")
_NON_FAMILY_KEYS = {
    "type", "samples", "family", "disposition", "kind", "phase", "block",
    "key", "class", "outcome", "version", "jax", "features", "le",
    "source", "url", "surface",
}
# Sample-line suffixes a doc may legitimately spell out for a histogram
# family; normalized back to the family name before the manifest check.
_SUFFIXES = ("_bucket", "_sum", "_count")


def manifest(project: Project) -> dict:
    """{family: (type, lineno)} parsed from obs/prom.py FAMILIES."""
    pf = project.file(PROM_PATH)
    if pf is None or pf.tree is None:
        return {}
    for node in pf.tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "FAMILIES"
            for t in node.targets
        ):
            try:
                raw = dict(ast.literal_eval(node.value))
            except (ValueError, SyntaxError):
                return {}
            return {k: (v, node.lineno) for k, v in raw.items()}
    return {}


def _code_families(project: Project) -> dict:
    """{family: (path, lineno)} constructed by the source."""
    out: dict = {}
    for pf in project.package_files():
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            # live histogram names: .observe("<name>", value, ...)
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "observe"
                and len(node.args) >= 2
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                fam = f"llmc_{node.args[0].value}_seconds"
                out.setdefault(fam, (pf.relpath, node.lineno))
            # gauge/family tables in metricsz/prom_families
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node.name in _FAMILY_FNS:
                for sub in ast.walk(node):
                    key = None
                    if isinstance(sub, ast.Dict):
                        for k in sub.keys:
                            if (
                                isinstance(k, ast.Constant)
                                and isinstance(k.value, str)
                            ):
                                key = k.value
                                if key not in _NON_FAMILY_KEYS:
                                    out.setdefault(
                                        f"llmc_{key}",
                                        (pf.relpath, k.lineno),
                                    )
                    elif isinstance(sub, ast.Subscript) and isinstance(
                        sub.ctx, ast.Store
                    ):
                        if (
                            isinstance(sub.slice, ast.Constant)
                            and isinstance(sub.slice.value, str)
                            and sub.slice.value not in _NON_FAMILY_KEYS
                        ):
                            out.setdefault(
                                f"llmc_{sub.slice.value}",
                                (pf.relpath, sub.lineno),
                            )
    out.setdefault("llmc_stat", (PROM_PATH, 1))  # rendered unconditionally
    return out


@checker(
    "metrics-docs",
    ("MD01", "MD02", "MD03", "MD04"),
    "metric families agree across code, the FAMILIES manifest, and docs",
)
def check(project: Project) -> list:
    findings: list = []
    fams = manifest(project)
    if not fams:
        findings.append(
            Finding(
                code="MD04",
                path=PROM_PATH,
                line=1,
                message=(
                    "could not parse the FAMILIES manifest from obs/prom.py"
                    " — the metric cross-check is blind"
                ),
                detail="FAMILIES :: unparsable",
            )
        )
        return findings
    # code vs manifest
    for fam, (path, lineno) in sorted(_code_families(project).items()):
        if fam not in fams:
            findings.append(
                Finding(
                    code="MD01",
                    path=path,
                    line=lineno,
                    message=(
                        f"metric family {fam} is constructed here but not "
                        "declared in obs/prom.py FAMILIES"
                    ),
                    detail=f"{fam} :: undeclared",
                )
            )
    # manifest vs docs
    doc_text = project.doc_texts().get(DOC_PATH, "")
    documented: set = set()
    for tok in _DOC_TOKEN_RE.findall(doc_text):
        for sfx in _SUFFIXES:
            if tok.endswith(sfx) and tok[: -len(sfx)] in fams:
                tok = tok[: -len(sfx)]
                break
        documented.add(tok)
    for fam, (_type, lineno) in sorted(fams.items()):
        if fam not in documented:
            findings.append(
                Finding(
                    code="MD02",
                    path=PROM_PATH,
                    line=lineno,
                    message=(
                        f"declared family {fam} has no row in "
                        f"{DOC_PATH}"
                    ),
                    detail=f"{fam} :: undocumented",
                )
            )
    for tok in sorted(documented):
        if tok not in fams and tok != "llmc":
            findings.append(
                Finding(
                    code="MD03",
                    path=DOC_PATH,
                    line=1,
                    message=(
                        f"{DOC_PATH} documents {tok} but obs/prom.py "
                        "FAMILIES does not declare it (stale or typo'd row)"
                    ),
                    detail=f"{tok} :: doc-only",
                )
            )
    return findings
