"""``python -m llm_consensus_tpu.analysis`` — the CI lint gate.

Exit codes: 0 = no unsuppressed findings; 1 = new findings (or a
baseline write was needed and ``--update-baseline`` wasn't passed);
2 = usage/config error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from llm_consensus_tpu.analysis import core


def _detect_root() -> Path:
    # analysis/__main__.py → analysis → llm_consensus_tpu → repo root
    return Path(__file__).resolve().parents[2]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m llm_consensus_tpu.analysis",
        description=(
            "Project-native static analysis: lock discipline, tracer "
            "hygiene, knob/fault/metric registries vs docs."
        ),
    )
    ap.add_argument(
        "--root", type=Path, default=None,
        help="repo root (default: auto-detected from the package location)",
    )
    ap.add_argument(
        "--baseline", type=Path, default=core.BASELINE_DEFAULT,
        help="baseline suppression file (default: analysis/baseline.txt)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding as new",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to exactly the current findings",
    )
    ap.add_argument(
        "--checks", default="",
        help="comma-separated checker names to run (default: all)",
    )
    ap.add_argument(
        "--list", action="store_true", help="list checkers and exit"
    )
    ap.add_argument(
        "-v", "--verbose", action="store_true",
        help="also print grandfathered (baseline-suppressed) findings",
    )
    ns = ap.parse_args(argv)

    if ns.list:
        for c in core.checkers():
            print(f"{c.name:16s} {','.join(c.codes):30s} {c.doc}")
        return 0

    try:
        project = core.Project(ns.root or _detect_root())
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    only = {s.strip() for s in ns.checks.split(",") if s.strip()} or None
    if only:
        known = {c.name for c in core.checkers()}
        unknown = only - known
        if unknown:
            print(
                f"error: unknown checkers {sorted(unknown)} "
                f"(known: {sorted(known)})",
                file=sys.stderr,
            )
            return 2

    findings = core.run_checkers(project, only)

    # Syntax errors are findings too — a file the AST can't parse is a
    # file every checker silently skipped.
    for pf in project.package_files():
        pf.tree  # force parse
        if pf.parse_error is not None:
            findings.append(
                core.Finding(
                    code="XX01",
                    path=pf.relpath,
                    line=pf.parse_error.lineno or 1,
                    message=f"syntax error: {pf.parse_error.msg}",
                    detail="syntax-error",
                )
            )

    if ns.update_baseline:
        core.save_baseline(ns.baseline, findings)
        print(
            f"baseline: wrote {len(findings)} fingerprint(s) to {ns.baseline}"
        )
        return 0

    baseline = set() if ns.no_baseline else core.load_baseline(ns.baseline)
    rep = core.apply_baseline(findings, baseline)

    for f in rep.new:
        print(f.render())
    if ns.verbose:
        for f in rep.grandfathered:
            print(f"{f.render()}  [grandfathered]")
    for fp in rep.stale:
        print(f"stale baseline entry (no longer fires): {fp}")

    counts: dict = {}
    for f in rep.new:
        counts[f.code] = counts.get(f.code, 0) + 1
    summary = ", ".join(f"{c}={n}" for c, n in sorted(counts.items()))
    print(
        f"analysis: {len(rep.new)} new finding(s)"
        + (f" ({summary})" if summary else "")
        + f", {len(rep.grandfathered)} grandfathered,"
        f" {len(rep.stale)} stale baseline entr(y/ies)"
    )
    return 0 if rep.ok else 1


if __name__ == "__main__":
    sys.exit(main())
