"""Lint framework: file model, finding/fingerprint shape, baseline IO.

Design points, sized to this project rather than to a generic linter:

  * **Pure AST + text.** Checkers never import the code under analysis —
    ``python -m llm_consensus_tpu.analysis`` runs in CI without jax (or
    any heavy dependency) ever initializing, and a module with an
    import-time bug still gets linted.
  * **Content-based fingerprints.** A finding's identity is
    ``CODE path :: detail`` where ``detail`` names the violating
    *thing* (``Class.method :: field``, a knob name, a fault kind) —
    never a line number — so the checked-in baseline survives unrelated
    edits above the finding and goes stale exactly when the violation
    itself moves or dies.
  * **Baseline = grandfather file, not an off switch.** Suppressed
    findings still print (as ``grandfathered``) under ``-v``; new
    findings fail the run; baseline entries that no longer fire are
    reported so the file shrinks monotonically.
  * **Inline escape hatch.** A source line carrying ``lint-ok: CODE``
    (e.g. ``# lint-ok: GS01 scheduler-owned``) suppresses that code on
    that line — for the handful of accesses whose safety argument is
    local and deliberate, where a baseline entry would hide the
    reasoning from the reader.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable, Optional

_LINT_OK_RE = re.compile(r"lint-ok:\s*([A-Z]{2}\d{2}(?:[ ,]+[A-Z]{2}\d{2})*)")


@dataclass
class Finding:
    """One checker hit. ``detail`` is the stable fingerprint payload."""

    code: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    detail: str

    @property
    def fingerprint(self) -> str:
        return f"{self.code} {self.path} :: {self.detail}"

    def render(self) -> str:
        return f"{self.code} {self.path}:{self.line}: {self.message}"


class PyFile:
    """One parsed source file (lazy AST, raw lines for comment checks)."""

    def __init__(self, abspath: Path, relpath: str):
        self.abspath = abspath
        self.relpath = relpath
        self.source = abspath.read_text(encoding="utf-8")
        self.lines = self.source.splitlines()
        self._tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None

    @property
    def tree(self) -> Optional[ast.AST]:
        if self._tree is None and self.parse_error is None:
            try:
                self._tree = ast.parse(self.source, filename=str(self.abspath))
            except SyntaxError as exc:
                self.parse_error = exc
        return self._tree

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, code: str, lineno: int) -> bool:
        m = _LINT_OK_RE.search(self.line_at(lineno))
        return bool(m) and code in m.group(1)


class Project:
    """The analyzed tree: package sources + test/doc/CI corpora."""

    PACKAGE = "llm_consensus_tpu"

    def __init__(self, root: Path):
        self.root = Path(root).resolve()
        self.package_dir = self.root / self.PACKAGE
        if not self.package_dir.is_dir():
            raise FileNotFoundError(
                f"{self.package_dir} not found — pass --root at the repo root"
            )
        self._files: Optional[list] = None

    def package_files(self) -> list:
        if self._files is None:
            self._files = [
                PyFile(p, p.relative_to(self.root).as_posix())
                for p in sorted(self.package_dir.rglob("*.py"))
            ]
        return self._files

    def file(self, relpath: str) -> Optional[PyFile]:
        for f in self.package_files():
            if f.relpath == relpath:
                return f
        return None

    def doc_texts(self) -> dict:
        """{relpath: text} for the operator-facing docs the doc-drift
        checkers cross-check (README + docs/*.md)."""
        out: dict = {}
        readme = self.root / "README.md"
        if readme.is_file():
            out["README.md"] = readme.read_text(encoding="utf-8")
        docs = self.root / "docs"
        if docs.is_dir():
            for p in sorted(docs.glob("*.md")):
                out[p.relative_to(self.root).as_posix()] = p.read_text(
                    encoding="utf-8"
                )
        return out

    def coverage_texts(self) -> dict:
        """{relpath: text} for everything that counts as exercising a
        fault site: the test suite, the dryrun lanes, and CI config."""
        out: dict = {}
        tests = self.root / "tests"
        if tests.is_dir():
            for p in sorted(tests.rglob("*.py")):
                out[p.relative_to(self.root).as_posix()] = p.read_text(
                    encoding="utf-8"
                )
        entry = self.root / "__graft_entry__.py"
        if entry.is_file():
            out["__graft_entry__.py"] = entry.read_text(encoding="utf-8")
        wf = self.root / ".github" / "workflows"
        if wf.is_dir():
            for p in sorted(wf.glob("*.y*ml")):
                out[p.relative_to(self.root).as_posix()] = p.read_text(
                    encoding="utf-8"
                )
        return out


@dataclass
class Checker:
    name: str
    codes: tuple
    doc: str
    fn: Callable


_CHECKERS: list = []


def checker(name: str, codes: tuple, doc: str):
    """Register a checker: ``fn(project) -> Iterable[Finding]``."""

    def wrap(fn):
        _CHECKERS.append(Checker(name, codes, doc, fn))
        return fn

    return wrap


def checkers() -> list:
    # Import for side effect: each module registers itself. Local so
    # importing core (e.g. from tests) stays cheap and cycle-free.
    from llm_consensus_tpu.analysis import (  # noqa: F401
        fault_coverage, guarded_state, knob_registry, metrics_docs,
        raw_primitives, tracer_hygiene,
    )

    return list(_CHECKERS)


def run_checkers(
    project: Project, only: Optional[Iterable[str]] = None
) -> list:
    findings: list = []
    for c in checkers():
        if only and c.name not in only:
            continue
        findings.extend(c.fn(project))
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.detail))
    return findings


# -- baseline ----------------------------------------------------------------

BASELINE_DEFAULT = Path(__file__).with_name("baseline.txt")

_BASELINE_HEADER = """\
# Grandfathered static-analysis findings (python -m llm_consensus_tpu.analysis).
# One fingerprint per line; entries suppress EXISTING findings only — new
# findings always fail. Regenerate with --update-baseline; entries that no
# longer fire are reported stale so this file only ever shrinks.
"""


def load_baseline(path: Path) -> set:
    if not Path(path).is_file():
        return set()
    out: set = set()
    for line in Path(path).read_text(encoding="utf-8").splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            out.add(line)
    return out


def save_baseline(path: Path, findings: Iterable[Finding]) -> None:
    fps = sorted({f.fingerprint for f in findings})
    Path(path).write_text(
        _BASELINE_HEADER + "".join(fp + "\n" for fp in fps),
        encoding="utf-8",
    )


@dataclass
class Report:
    new: list = field(default_factory=list)
    grandfathered: list = field(default_factory=list)
    stale: list = field(default_factory=list)  # baseline entries that no longer fire

    @property
    def ok(self) -> bool:
        return not self.new


def apply_baseline(findings: list, baseline: set) -> Report:
    rep = Report()
    fired = set()
    for f in findings:
        fp = f.fingerprint
        if fp in baseline:
            fired.add(fp)
            rep.grandfathered.append(f)
        else:
            rep.new.append(f)
    rep.stale = sorted(baseline - fired)
    return rep
