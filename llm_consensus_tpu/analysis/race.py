"""FastTrack-style vector-clock happens-before race detection.

The PR-14 sanitizer proves lock *placement* (static ``GS``) and lock
*ordering* (runtime cycle graph); this module closes the remaining gap:
**ordering races on guarded state** — a read and a write of the same
field with no happens-before edge between them, which no lock-order
cycle reveals and which the static checker cannot see when one access
hides behind a helper or an annotated-deliberate path goes stale.

The guarded-state checker's **static field inventory is the dynamic
instrumentation point set**: :func:`inventory` re-runs the ``GS`` scan
(pure AST, cached) over the package, and :func:`attach` wraps each
inventoried class's ``__getattribute__`` / ``__setattr__`` so every
rebind (write) and load (read) of a ``# guarded by:`` field reports to
the detector. Granularity note: container *mutations*
(``self._queue.append``) surface as reads of the field binding — two
off-lock mutators therefore need the schedule explorer's invariant
fixtures, while scalar read/write races (the ``+=`` lost-update class
and torn multi-field invariants) are caught here directly.

Happens-before edges come from the sanitizer seam: lock release ⇒ later
acquire (FastTrack's lock clocks), explicit notify ⇒ wake on conditions
and set ⇒ wait-return on events (the PR-15 wait/notify bookkeeping
fix), and thread fork/join from the cooperative scheduler. Epochs keep
the common same-thread path O(1); a read set promotes to a full vector
clock only when genuinely shared (the FastTrack adaptive
representation).

False-positive discipline: an access whose source line carries
``# lint-ok: GS01`` (the deliberate lock-free reads the static checker
already documents) or ``# race-ok`` is excluded at report time — the
safety argument stays inline, shared by both analyses. Races accumulate
in :attr:`RaceDetector.races`; a schedule session raises
:class:`RaceError` at exit so the first racy interleaving fails with
both access sites in hand.
"""

from __future__ import annotations

import linecache
import sys
import threading
import weakref
from pathlib import Path
from typing import Callable, Iterable, Optional


class RaceError(AssertionError):
    """One or more happens-before races on guarded fields."""

    def __init__(self, races: list):
        self.races = list(races)
        lines = []
        for r in self.races[:8]:
            lines.append(
                f"  {r['kind']} race on {r['label']}: "
                f"{r['prev_site'][0]}:{r['prev_site'][1]} vs "
                f"{r['site'][0]}:{r['site'][1]}"
            )
        more = len(self.races) - len(lines)
        if more > 0:
            lines.append(f"  … and {more} more")
        super().__init__(
            f"{len(self.races)} happens-before race(s) on guarded fields\n"
            + "\n".join(lines)
        )


class _Var:
    """Per-(object, field) access state: write epoch + adaptive reads."""

    __slots__ = (
        "wt", "wc", "wsite", "rt", "rc", "rsite", "rvc", "rsites",
    )

    def __init__(self):
        self.wt = None
        self.wc = 0
        self.wsite = None
        self.rt = None
        self.rc = 0
        self.rsite = None
        self.rvc = None
        self.rsites = None


def _suppressed(site) -> bool:
    line = linecache.getline(site[0], site[1])
    return "race-ok" in line or ("lint-ok:" in line and "GS01" in line)


class RaceDetector:
    """Process-wide happens-before state: per-thread vector clocks,
    per-sync-object clocks, per-variable epochs. Thread identity comes
    from ``tid_fn`` — the cooperative scheduler's stable tids inside a
    schedule session (idents recycle, tids don't), ``get_ident``
    otherwise. All hooks are cheap no-ops for threads ``tid_fn`` does
    not know (returns None): uncontrolled helper threads never
    corrupt the clock space."""

    def __init__(self, tid_fn: Optional[Callable] = None):
        self._mu = threading.Lock()
        self.tid_fn = tid_fn or threading.get_ident
        self._clocks: dict = {}   # tid -> {tid: int}
        self._locks: dict = {}    # lock id -> clock
        self._sync: dict = {}     # cond/event id -> accumulated clock
        self._vars: dict = {}     # (obj id, field) -> _Var
        self._tracked: dict = {}  # obj id -> weakref.finalize (or None)
        self._dead: list = []     # collected obj ids awaiting purge
        self._seen: set = set()
        self.races: list = []

    # -- object-identity hygiene ----------------------------------------------
    #
    # ``id(obj)`` recycles: epochs of a COLLECTED object must not alias
    # onto a new object allocated at the same address (a dead thread's
    # stale write epoch would false-positive the new object's first
    # properly-locked access). A ``weakref.finalize`` per tracked object
    # queues its id for purge — append-only from the finalizer (which
    # may fire mid-GC while THIS thread holds ``_mu``; taking the lock
    # there would self-deadlock), drained under ``_mu`` on the next
    # access before the id can be re-observed.

    def _track_locked(self, obj, oid) -> None:
        if oid in self._tracked:
            return
        try:
            fin = weakref.finalize(obj, self._dead.append, oid)
        except TypeError:
            fin = None  # not weakref-able: entries live for the session
        self._tracked[oid] = fin

    def _purge_dead_locked(self) -> None:
        dead = set()
        while self._dead:
            dead.add(self._dead.pop())
        for oid in dead:
            self._tracked.pop(oid, None)
        for key in [k for k in self._vars if k[0] in dead]:
            del self._vars[key]

    # -- clock helpers --------------------------------------------------------

    def _ct(self, t) -> dict:
        c = self._clocks.get(t)
        if c is None:
            c = self._clocks[t] = {t: 1}
        return c

    @staticmethod
    def _join(dst: dict, src: dict) -> None:
        for k, v in src.items():
            if v > dst.get(k, 0):
                dst[k] = v

    # -- sync edges -----------------------------------------------------------

    def on_acquire(self, t, m) -> None:
        if t is None:
            return
        with self._mu:
            lm = self._locks.get(m)
            if lm:
                self._join(self._ct(t), lm)

    def on_release(self, t, m) -> None:
        if t is None:
            return
        with self._mu:
            c = self._ct(t)
            self._locks[m] = dict(c)
            c[t] = c.get(t, 0) + 1

    def on_notify(self, t, s) -> None:
        if t is None:
            return
        with self._mu:
            c = self._ct(t)
            acc = self._sync.setdefault(s, {})
            self._join(acc, c)
            c[t] = c.get(t, 0) + 1

    def on_wake(self, t, s) -> None:
        if t is None:
            return
        with self._mu:
            acc = self._sync.get(s)
            if acc:
                self._join(self._ct(t), acc)

    def on_fork(self, parent, child) -> None:
        with self._mu:
            pc = self._ct(parent)
            cc = dict(pc)
            cc[child] = 1
            self._clocks[child] = cc
            pc[parent] = pc.get(parent, 0) + 1

    def on_join(self, parent, child) -> None:
        with self._mu:
            cc = self._clocks.get(child)
            if cc:
                self._join(self._ct(parent), cc)

    def on_thread_end(self, t) -> None:
        # The final clock stays in _clocks for a later on_join.
        pass

    # -- variable accesses ----------------------------------------------------

    def _race(self, kind, label, prev_site, site) -> None:
        if prev_site is None or site is None:
            return
        if _suppressed(prev_site) or _suppressed(site):
            return
        key = (kind, label, prev_site, site)
        if key in self._seen:
            return
        self._seen.add(key)
        self.races.append({
            "kind": kind,
            "label": label,
            "prev_site": prev_site,
            "site": site,
        })

    def on_read(self, obj, field: str, site, label: str) -> None:
        t = self.tid_fn()
        if t is None:
            return
        with self._mu:
            if self._dead:
                self._purge_dead_locked()
            c = self._ct(t)
            oid = id(obj)
            v = self._vars.get((oid, field))
            if v is None:
                self._track_locked(obj, oid)
                v = self._vars[(oid, field)] = _Var()
            if v.wt is not None and v.wt != t and v.wc > c.get(v.wt, 0):
                self._race("write-read", label, v.wsite, site)
            if v.rvc is not None:
                v.rvc[t] = c.get(t, 0)
                v.rsites[t] = site
            elif v.rt is None or v.rt == t or v.rc <= c.get(v.rt, 0):
                v.rt, v.rc, v.rsite = t, c.get(t, 0), site
            else:
                v.rvc = {v.rt: v.rc, t: c.get(t, 0)}
                v.rsites = {v.rt: v.rsite, t: site}
                v.rt = None

    def on_write(self, obj, field: str, site, label: str) -> None:
        t = self.tid_fn()
        if t is None:
            return
        with self._mu:
            if self._dead:
                self._purge_dead_locked()
            c = self._ct(t)
            oid = id(obj)
            v = self._vars.get((oid, field))
            if v is None:
                self._track_locked(obj, oid)
                v = self._vars[(oid, field)] = _Var()
            if v.wt is not None and v.wt != t and v.wc > c.get(v.wt, 0):
                self._race("write-write", label, v.wsite, site)
            if v.rvc is not None:
                for u, rc in v.rvc.items():
                    if u != t and rc > c.get(u, 0):
                        self._race(
                            "read-write", label, v.rsites.get(u), site
                        )
                        break
            elif v.rt is not None and v.rt != t and v.rc > c.get(v.rt, 0):
                self._race("read-write", label, v.rsite, site)
            v.wt, v.wc, v.wsite = t, c.get(t, 0), site
            v.rt, v.rc, v.rsite = None, 0, None
            v.rvc = None
            v.rsites = None


# -- guarded-field inventory (the GS scan, reused dynamically) ----------------

_inventory_cache: Optional[dict] = None


def inventory() -> dict:
    """{(module_name, class_name): {field, …}} for every class the
    guarded-state checker sees — computed from source (pure AST), so
    the dynamic point set can never drift from the static one."""
    global _inventory_cache
    if _inventory_cache is not None:
        return _inventory_cache
    import ast

    from llm_consensus_tpu.analysis.core import Project
    from llm_consensus_tpu.analysis.guarded_state import _scan_init

    import llm_consensus_tpu

    root = Path(llm_consensus_tpu.__file__).resolve().parent.parent
    out: dict = {}
    try:
        project = Project(root)
    except FileNotFoundError:
        _inventory_cache = out
        return out
    for pf in project.package_files():
        tree = pf.tree
        if tree is None:
            continue
        mod = pf.relpath[:-3].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
            info = _scan_init(pf, cls)
            if info is None:
                continue
            out[(mod, cls.name)] = set(info.guarded)
    _inventory_cache = out
    return out


# -- class instrumentation ----------------------------------------------------

_detector: Optional[RaceDetector] = None
_instrumented: dict = {}  # cls -> (orig __getattribute__, orig __setattr__)


def detector() -> Optional[RaceDetector]:
    return _detector


def instrument_class(cls, fields: Iterable) -> None:
    """Wrap ``cls`` so accesses of ``fields`` report to the attached
    detector (fast-path: one set lookup + one global None-check when
    detached). Idempotent; :func:`detach` restores the originals."""
    if cls in _instrumented:
        return
    fieldset = frozenset(fields)
    orig_get = cls.__getattribute__
    orig_set = cls.__setattr__
    cls_name = cls.__name__

    def __getattribute__(self, name):
        if name in fieldset:
            det = _detector
            if det is not None:
                fr = sys._getframe(1)
                det.on_read(
                    self, name, (fr.f_code.co_filename, fr.f_lineno),
                    f"{cls_name}.{name}",
                )
        return orig_get(self, name)

    def __setattr__(self, name, value):
        if name in fieldset:
            det = _detector
            if det is not None:
                fr = sys._getframe(1)
                det.on_write(
                    self, name, (fr.f_code.co_filename, fr.f_lineno),
                    f"{cls_name}.{name}",
                )
        orig_set(self, name, value)

    cls.__getattribute__ = __getattribute__
    cls.__setattr__ = __setattr__
    _instrumented[cls] = (orig_get, orig_set)


def attach(det: RaceDetector, extra: Iterable = ()) -> None:
    """Install ``det`` as the process detector and instrument every
    already-imported inventoried class (plus ``extra``: an iterable of
    ``(cls, fields)`` pairs for harness-local fixture classes)."""
    import importlib

    from llm_consensus_tpu.analysis import sanitizer

    global _detector
    for (mod, cls_name), fields in inventory().items():
        m = sys.modules.get(mod)
        if m is None:
            # A fixture that lazy-imports its subject module must not
            # run its first schedule uninstrumented: import the
            # inventoried module now (skip ones whose deps are absent).
            try:
                m = importlib.import_module(mod)
            except Exception:  # noqa: BLE001 — optional heavy deps
                continue
        cls = getattr(m, cls_name, None)
        if isinstance(cls, type):
            instrument_class(cls, fields)
    for cls, fields in extra:
        instrument_class(cls, fields)
    _detector = det
    sanitizer.set_race_detector(det)


def detach() -> None:
    """Remove the detector and restore every instrumented class."""
    from llm_consensus_tpu.analysis import sanitizer

    global _detector
    _detector = None
    sanitizer.set_race_detector(None)
    for cls, (orig_get, orig_set) in _instrumented.items():
        cls.__getattribute__ = orig_get
        cls.__setattr__ = orig_set
    _instrumented.clear()


__all__ = [
    "RaceDetector", "RaceError", "inventory", "instrument_class",
    "attach", "detach", "detector",
]
