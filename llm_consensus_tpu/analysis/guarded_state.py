"""GS: guarded-state lock discipline.

A class opts in by annotating field assignments in ``__init__`` with a
``# guarded by: <lock-attr>`` comment::

    self._queue = []      # guarded by: _lock
    self._stats = {...}   # guarded by: _lock

From then on, every read or write of ``self._queue`` anywhere in the
class must sit lexically inside ``with self._lock:`` (or a recognized
alias — see below), with three deliberate escape hatches:

  * ``__init__`` itself (construction happens before publication);
  * methods whose name ends in ``_locked`` — the project's standing
    convention for "caller holds the lock" (the checker still verifies
    their *callers* at the call site's own accesses; the runtime
    sanitizer's :func:`~llm_consensus_tpu.analysis.sanitizer.assert_held`
    covers the dynamic half);
  * a line carrying ``# lint-ok: GS01 <reason>`` for accesses whose
    safety argument is local and deliberate.

Alias resolution: ``self._work = threading.Condition(self._lock)`` (or
the sanitizer factory form ``make_condition(name, self._lock)``) makes
holding ``_work`` equivalent to holding ``_lock`` — both names resolve
to one canonical rank, so ``with self._work:`` guards ``_lock``-guarded
fields. A bare ``Condition()`` is its own lock.

Findings:
  GS01 — guarded field read/written outside its lock
  GS02 — ``guarded by:`` names an attribute never assigned a lock
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from llm_consensus_tpu.analysis.core import Finding, Project, checker

_GUARD_RE = re.compile(r"#\s*guarded by:\s*(\w+)")

_LOCK_FACTORIES = ("Lock", "RLock", "Condition")
_SAN_FACTORIES = ("make_lock", "make_rlock", "make_condition")


def _call_name(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _ClassInfo:
    def __init__(self):
        self.guarded: dict = {}  # field -> (canonical lock, decl lineno)
        self.locks: dict = {}  # lock attr -> canonical lock attr
        self.decl_order: list = []

    def canonical(self, name: str) -> str:
        seen = set()
        while name in self.locks and self.locks[name] != name:
            if name in seen:
                break
            seen.add(name)
            name = self.locks[name]
        return name


def _scan_init(pf, cls: ast.ClassDef) -> Optional[_ClassInfo]:
    init = next(
        (
            n
            for n in cls.body
            if isinstance(n, ast.FunctionDef) and n.name == "__init__"
        ),
        None,
    )
    if init is None:
        return None
    info = _ClassInfo()
    for node in ast.walk(init):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = _self_attr(node.targets[0])
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target = _self_attr(node.target)
        else:
            continue
        if target is None:
            continue
        # Lock/condition construction → lock attr (+ alias when the
        # condition wraps another self lock).
        if isinstance(node.value, ast.Call):
            cname = _call_name(node.value)
            if cname in _LOCK_FACTORIES + _SAN_FACTORIES:
                info.locks.setdefault(target, target)
                if cname in ("Condition", "make_condition"):
                    for arg in node.value.args:
                        wrapped = _self_attr(arg)
                        if wrapped is not None:
                            info.locks[target] = wrapped
                            info.locks.setdefault(wrapped, wrapped)
        m = _GUARD_RE.search(pf.line_at(node.lineno))
        if m:
            info.guarded[target] = (m.group(1), node.lineno)
            info.decl_order.append(target)
    return info if info.guarded else None


class _MethodVisitor(ast.NodeVisitor):
    """Walk one method tracking the lexically-held canonical lock set."""

    def __init__(self, pf, relpath, cls_name, method, info, findings):
        self.pf = pf
        self.relpath = relpath
        self.cls_name = cls_name
        self.method = method
        self.info = info
        self.findings = findings
        self.held: list = []

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and attr in self.info.locks:
                acquired.append(self.info.canonical(attr))
        self.held.extend(acquired)
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None and attr in self.info.guarded:
            lock, _decl = self.info.guarded[attr]
            need = self.info.canonical(lock)
            if need not in self.held and not self.pf.suppressed(
                "GS01", node.lineno
            ):
                self.findings.append(
                    Finding(
                        code="GS01",
                        path=self.relpath,
                        line=node.lineno,
                        message=(
                            f"{self.cls_name}.{attr} is guarded by "
                            f"self.{lock} but accessed off-lock in "
                            f"{self.method}()"
                        ),
                        detail=f"{self.cls_name}.{self.method} :: {attr}",
                    )
                )
        self.generic_visit(node)


@checker(
    "guarded-state",
    ("GS01", "GS02"),
    "fields annotated '# guarded by: <lock>' only touched under the lock",
)
def check(project: Project) -> list:
    findings: list = []
    for pf in project.package_files():
        tree = pf.tree
        if tree is None:
            continue
        for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
            info = _scan_init(pf, cls)
            if info is None:
                continue
            for fname in info.decl_order:
                lock, lineno = info.guarded[fname]
                if info.canonical(lock) not in info.locks:
                    findings.append(
                        Finding(
                            code="GS02",
                            path=pf.relpath,
                            line=lineno,
                            message=(
                                f"{cls.name}.{fname}: 'guarded by: {lock}' "
                                f"names an attribute never assigned a lock "
                                f"in __init__"
                            ),
                            detail=f"{cls.name} :: {fname} :: {lock}",
                        )
                    )
            for node in cls.body:
                if not isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if node.name == "__init__" or node.name.endswith("_locked"):
                    continue
                _MethodVisitor(
                    pf, pf.relpath, cls.name, node.name, info, findings
                ).visit(node)
    return findings
