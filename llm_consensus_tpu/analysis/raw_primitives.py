"""SA: raw synchronization-primitive construction is forbidden.

The sanitizer factories (``analysis/sanitizer.py`` ``make_lock`` /
``make_rlock`` / ``make_condition`` / ``make_event``) are the seam that
gives every lock a ROLE name in the order graph, hands the runtime
sanitizer its instrumentation, and — inside a schedule-exploration
session (``analysis/schedule.py``) — swaps in cooperative primitives so
the model checker sees the whole process. A raw
``threading.Lock()`` / ``RLock()`` / ``Condition()`` / ``Event()``
anywhere else is a lock the deadlock detector cannot rank, the race
detector cannot order, and the scheduler cannot preempt: coverage that
silently regressed. PR 15 migrated every such construction; this
checker keeps it migrated.

Findings:
  SA01 — raw ``threading.{Lock,RLock,Condition,Event}(...)`` constructed
         outside ``analysis/`` and the explicit allowlist

The allowlist is deliberately tiny and lives here, not in the baseline:
an entry means "this module IS the instrumentation substrate", not
"this violation is grandfathered". ``threading.local`` /
``Semaphore`` / ``Thread`` are not restricted — they carry no lock rank
(the scheduler intercepts ``Thread.start`` dynamically instead).
"""

from __future__ import annotations

import ast

from llm_consensus_tpu.analysis.core import Finding, Project, checker

_PRIMITIVES = ("Lock", "RLock", "Condition", "Event")

# Paths (exact file or trailing-slash directory prefix) allowed to
# construct raw primitives: the instrumentation substrate itself must
# bottom out on real threading objects.
ALLOWLIST = (
    "llm_consensus_tpu/analysis/",
)


def _allowed(relpath: str) -> bool:
    for entry in ALLOWLIST:
        if entry.endswith("/"):
            if relpath.startswith(entry):
                return True
        elif relpath == entry:
            return True
    return False


def _threading_aliases(tree: ast.AST) -> tuple:
    """(module aliases of ``threading``, {local name: primitive} from
    ``from threading import Lock as L``)."""
    mods: set = set()
    names: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "threading":
                    mods.add(a.asname or "threading")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "threading":
                for a in node.names:
                    if a.name in _PRIMITIVES:
                        names[a.asname or a.name] = a.name
    return mods, names


@checker(
    "raw-primitives",
    ("SA01",),
    "locks/conditions/events built via the sanitizer factories only",
)
def check(project: Project) -> list:
    findings: list = []
    for pf in project.package_files():
        if _allowed(pf.relpath):
            continue
        tree = pf.tree
        if tree is None:
            continue
        mods, names = _threading_aliases(tree)
        if not mods and not names:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            prim = ""
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in _PRIMITIVES
                and isinstance(fn.value, ast.Name)
                and fn.value.id in mods
            ):
                prim = fn.attr
            elif isinstance(fn, ast.Name) and fn.id in names:
                prim = names[fn.id]
            if not prim or pf.suppressed("SA01", node.lineno):
                continue
            factory = {
                "Lock": "make_lock", "RLock": "make_rlock",
                "Condition": "make_condition", "Event": "make_event",
            }[prim]
            findings.append(
                Finding(
                    code="SA01",
                    path=pf.relpath,
                    line=node.lineno,
                    message=(
                        f"raw threading.{prim}() — construct it via "
                        f"sanitizer.{factory}(<role>) so the sanitizer, "
                        "race detector, and schedule explorer see it"
                    ),
                    detail=f"threading.{prim} :: line-site "
                           f"{_site_detail(pf, node.lineno)}",
                )
            )
    return findings


def _site_detail(pf, lineno: int) -> str:
    """Content-stable detail: the stripped source line (a raw
    construction is identified by what it assigns, not where)."""
    return pf.line_at(lineno).strip()[:80]
