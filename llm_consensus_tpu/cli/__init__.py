from llm_consensus_tpu.cli.main import main

__all__ = ["main"]
