"""``llm-consensus distill`` — the offline half of the data flywheel.

One shot: scan the serving journal (``data/<run-id>/`` manifests),
build the deduplicated (panel-answers → judge-verdict) corpus, distill
the journaled judge onto a student model (flywheel/distill.py), and
save a versioned checkpoint ready for the gateway's ``POST /v1/swap``.
Prints one JSON summary (corpus counts, holdout loss before/after, the
checkpoint's version + path) so a cron job or the CI lane can assert
``holdout_loss_after < holdout_loss_before`` and feed the checkpoint
path straight to the swap endpoint.

The run is CPU-viable by construction: tiny presets random-init when
``--checkpoints`` has no weights, so the whole loop (serve → corpus →
distill → swap) exercises in CI without TPU time.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, TextIO

from llm_consensus_tpu.utils import knobs


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="llm-consensus distill",
        description="Distill the journaled judge onto a student model "
        "and emit a hot-swappable versioned checkpoint.",
    )
    p.add_argument(
        "--data-dir", default=None,
        help="serving journal root to scan (default LLMC_DATA_DIR)",
    )
    p.add_argument(
        "--student", default="tiny-llama",
        help="student model preset (default tiny-llama)",
    )
    p.add_argument(
        "--teacher", default=None,
        help="teacher preset (default: the student — self-distillation "
        "from the journaled verdicts)",
    )
    p.add_argument(
        "--out", default=None,
        help="checkpoint output root (default <data-dir>/_artifacts/"
        "distill); versions land at <out>/vNNNN/",
    )
    p.add_argument(
        "--checkpoints", default=None,
        help="serving checkpoint root to warm-start student/teacher "
        "from (random-init when absent)",
    )
    p.add_argument("--steps", type=int, default=None,
                   help="train steps (default LLMC_DISTILL_STEPS)")
    p.add_argument("--lr", type=float, default=None,
                   help="learning rate (default LLMC_DISTILL_LR)")
    p.add_argument("--batch", type=int, default=None,
                   help="global batch size (default LLMC_DISTILL_BATCH)")
    p.add_argument("--seq", type=int, default=None,
                   help="sequence length (default LLMC_DISTILL_SEQ)")
    p.add_argument(
        "--temperature", type=float, default=None,
        help="soft-target temperature (default LLMC_DISTILL_TEMP)",
    )
    p.add_argument(
        "--alpha", type=float, default=None,
        help="KL weight in the KL/CE mix (default LLMC_DISTILL_ALPHA)",
    )
    p.add_argument(
        "--holdout", type=float, default=None,
        help="holdout fraction (default LLMC_DISTILL_HOLDOUT)",
    )
    p.add_argument("--quiet", action="store_true",
                   help="suppress progress lines (JSON summary only)")
    return p


def distill_main(
    argv: list,
    stdout: Optional[TextIO] = None,
    stderr: Optional[TextIO] = None,
    install_signal_handlers: bool = True,  # noqa: ARG001 — CLI entry parity
) -> int:
    stdout = sys.stdout if stdout is None else stdout
    stderr = sys.stderr if stderr is None else stderr
    args = build_parser().parse_args(argv)

    from llm_consensus_tpu.flywheel.corpus import ARTIFACTS_DIRNAME, build_corpus

    data_dir = args.data_dir or knobs.get_str("LLMC_DATA_DIR")
    log = (lambda _m: None) if args.quiet else (
        lambda m: (stderr.write(f"{m}\n"), stderr.flush())
    )
    log(f"scanning {data_dir} ...")
    corpus = build_corpus(data_dir=data_dir, holdout=args.holdout)
    summary: dict = {"corpus": corpus.summary()}
    if not corpus.train:
        # An empty corpus is an operator signal, not a crash: the lane
        # distinguishes "nothing served yet" (exit 2) from a real
        # failure (exception → exit 1 upstream).
        summary["error"] = "no training examples in corpus"
        stdout.write(json.dumps(summary, indent=2) + "\n")
        return 2
    out_dir = args.out
    if out_dir is None:
        import os

        out_dir = os.path.join(data_dir, ARTIFACTS_DIRNAME, "distill")
    result = run_corpus_distill(corpus, args, out_dir, log)
    summary.update(result)
    stdout.write(json.dumps(summary, indent=2) + "\n")
    return 0


def run_corpus_distill(corpus, args, out_dir: str, log) -> dict:
    """The jax-touching half, split out so corpus-only failures (exit 2)
    never pay an engine import."""
    from llm_consensus_tpu.flywheel.distill import run_distill

    return run_distill(
        corpus,
        student=args.student,
        teacher=args.teacher,
        out_dir=out_dir,
        checkpoint_dir=args.checkpoints,
        steps=args.steps,
        lr=args.lr,
        batch=args.batch,
        seq=args.seq,
        temperature=args.temperature,
        alpha=args.alpha,
        log=log,
    )
