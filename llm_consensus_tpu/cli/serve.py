"""``llm-consensus serve`` — the resident consensus service.

Where the plain CLI pays a full process lifecycle per prompt, ``serve``
builds the registry and engines once and keeps them warm behind the HTTP
gateway (llm_consensus_tpu/serve/): compiled programs, weights, and the
continuous batcher stay resident, and many concurrent consensus runs
multiplex onto them.

Capacity model: each concurrent run sends one stream per panel model to
that preset's continuous batcher (``max_batch`` slots per preset), so
the admission concurrency cap and the batcher depth are the SAME budget
viewed from two layers. ``--max-batch`` (or ``LLMC_MAX_BATCH``) sets the
batcher depth; the default admission cap is derived from it, and an
explicit ``--max-concurrency`` that oversubscribes the batcher is
rejected at startup — a misconfigured server must fail fast, not queue
inside the submit path where nothing can shed load.

SIGTERM/SIGINT drain gracefully: stop admitting (new requests get 503 +
``Retry-After``), finish in-flight runs, flush every ``data/<run-id>/``,
then exit.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
from dataclasses import dataclass
from typing import Optional, TextIO

from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu import ui
from llm_consensus_tpu.utils import knobs

DEFAULT_MAX_BATCH = 8
# HTTP-only panels have no device budget to derive a cap from; this is a
# plain thread-count default, unrelated to the batcher depth.
DEFAULT_HTTP_CONCURRENCY = 8
DEFAULT_QUEUE_DEPTH = 16
DEFAULT_CACHE_SIZE = 256
DEFAULT_CACHE_TTL_S = 300.0


@dataclass
class ServeConfig:
    models: list[str]
    judge: str
    host: str = "127.0.0.1"
    port: int = 8080
    timeout: float = 120.0
    max_tokens: Optional[int] = None
    system: str = ""
    data_dir: str = "data"
    no_save: bool = False
    max_batch: int = DEFAULT_MAX_BATCH
    max_concurrency: Optional[int] = None  # None → derived from max_batch
    queue_depth: int = DEFAULT_QUEUE_DEPTH
    cache_size: int = DEFAULT_CACHE_SIZE
    cache_ttl: float = DEFAULT_CACHE_TTL_S
    quiet: bool = False
    events: bool = False
    prefill_budget: Optional[int] = None  # None → LLMC_PREFILL_BUDGET
    judge_overlap: bool = False
    announce: str = ""  # fleet router URL to heartbeat-register with
    draft: str = ""  # speculative decoding ("lookup" batches; see --draft)
    spec_k: Optional[int] = None  # draft-length ceiling per round
    no_live: bool = False  # disable the /metricsz live plane + blackbox
    blackbox_dir: str = ""  # flight-recorder dump dir (LLMC_BLACKBOX_DIR)
    slo_ttft_p99: Optional[float] = None  # SLO burn threshold seconds
    disagg: bool = False  # disaggregated prefill/decode (LLMC_DISAGG)


def _env_max_batch() -> int:
    n = knobs.get_int("LLMC_MAX_BATCH", 0) or knobs.get_int(
        "LLMC_BATCH_STREAMS", 0
    )
    return n if n else DEFAULT_MAX_BATCH


def parse_serve_args(argv: list[str]) -> ServeConfig:
    from llm_consensus_tpu.cli.main import DEFAULT_JUDGE, DEFAULT_TIMEOUT_S, CLIError

    parser = argparse.ArgumentParser(
        prog="llm-consensus serve",
        description="Serve consensus over HTTP from resident engines.",
    )
    parser.add_argument("--models", "-models", default="", metavar="LIST",
                        help="Comma-separated panel models (required)")
    parser.add_argument("--judge", "-judge", default=DEFAULT_JUDGE,
                        help="Model for consensus synthesis")
    parser.add_argument("--host", "-host", default="127.0.0.1",
                        help="Bind address (default 127.0.0.1)")
    parser.add_argument("--port", "-port", type=int, default=8080,
                        help="Bind port (0 = OS-assigned)")
    parser.add_argument("--timeout", "-timeout", type=int,
                        default=DEFAULT_TIMEOUT_S,
                        help="Default per-request timeout in seconds")
    parser.add_argument("--max-tokens", "-max-tokens", type=int, default=None,
                        help="Default max tokens generated per model")
    parser.add_argument("--system", "-system", default="",
                        help="Default system prompt for panel models")
    parser.add_argument("--data-dir", "-data-dir", default="data",
                        help="Directory for per-request run dirs")
    parser.add_argument("--no-save", "-no-save", action="store_true",
                        help="Don't persist run dirs")
    parser.add_argument("--max-batch", "-max-batch", type=int, default=None,
                        help="Continuous-batcher slots per tpu preset "
                             f"(default LLMC_MAX_BATCH or {DEFAULT_MAX_BATCH})")
    parser.add_argument("--max-concurrency", "-max-concurrency", type=int,
                        default=None,
                        help="Concurrent consensus runs (default derived "
                             "from --max-batch / panel shape)")
    parser.add_argument("--queue-depth", "-queue-depth", type=int,
                        default=DEFAULT_QUEUE_DEPTH,
                        help="Requests allowed to wait for a slot before "
                             "429s (0 = reject when saturated)")
    parser.add_argument("--cache-size", "-cache-size", type=int,
                        default=DEFAULT_CACHE_SIZE,
                        help="Consensus result cache entries (0 disables)")
    parser.add_argument("--cache-ttl", "-cache-ttl", type=float,
                        default=DEFAULT_CACHE_TTL_S,
                        help="Cache entry TTL in seconds")
    parser.add_argument("--prefill-budget", "-prefill-budget", type=int,
                        default=None, metavar="TOKENS",
                        help="Interleaved admission prefill: dispatch at "
                             "most this many prompt tokens of a new "
                             "stream's prefill between decode chunks, so "
                             "resident streams keep decoding during "
                             "admission (0/unset = classic; "
                             "LLMC_PREFILL_BUDGET equivalent)")
    parser.add_argument("--judge-overlap", "-judge-overlap",
                        action="store_true",
                        help="Prefill each run's judge prompt "
                             "incrementally as panel answers arrive "
                             "(tpu judges); LLMC_JUDGE_OVERLAP=1 "
                             "equivalent")
    parser.add_argument("--draft", "-draft", default="", metavar="SPEC",
                        help="Speculative decoding for tpu models: "
                             "'lookup' (prompt-lookup n-grams — zero draft "
                             "cost, composes with the continuous batcher: "
                             "pools run batched spec rounds), a draft "
                             "preset for every target, or target=draft "
                             "pairs (a=b,c=d). Greedy output is "
                             "token-exact; LLMC_DRAFT equivalent")
    parser.add_argument("--spec-k", "-spec-k", type=int, default=None,
                        metavar="K",
                        help="Speculative draft-length ceiling per round "
                             "(default LLMC_SPEC_K or 4); adaptive k walks "
                             "a pow2 ladder below it")
    parser.add_argument("--disagg", "-disagg", action="store_true",
                        help="Disaggregated prefill/decode serving: split "
                             "each tpu preset's device slice into a "
                             "dedicated prefill sub-mesh and a resident "
                             "decode sub-mesh; finished prefix KV hands "
                             "off cross-mesh into the decode pool's paged "
                             "arena, so admission prefill leaves the "
                             "decode chips (needs >= 2 devices per "
                             "preset; implies LLMC_KV_POOL=1; LLMC_DISAGG "
                             "equivalent — LLMC_DISAGG_FRACTION sizes the "
                             "prefill share, default 0.5)")
    parser.add_argument("--announce", "-announce", default="", metavar="URL",
                        help="Fleet router base URL to register with by "
                             "periodic heartbeat (load_score + drain "
                             "state; LLMC_FLEET_ANNOUNCE equivalent, "
                             "LLMC_FLEET_HEARTBEAT_S sets the cadence)")
    parser.add_argument("--no-live", "-no-live", action="store_true",
                        help="Disable the live observability plane "
                             "(GET /metricsz histograms + the always-on "
                             "flight recorder + chip-time attribution; "
                             "LLMC_LIVE=0 LLMC_BLACKBOX=0 LLMC_ATTRIB=0 "
                             "equivalent)")
    parser.add_argument("--blackbox-dir", "-blackbox-dir", default="",
                        metavar="DIR",
                        help="Flight-recorder dump directory "
                             "(default LLMC_BLACKBOX_DIR or data/_artifacts/blackbox)")
    parser.add_argument("--slo-ttft-p99", "-slo-ttft-p99", type=float,
                        default=None, metavar="SECONDS",
                        help="SLO burn trigger: p99 TTFT over this for "
                             "LLMC_SLO_WINDOWS consecutive windows dumps "
                             "the flight recorder (LLMC_SLO_TTFT_P99_S "
                             "equivalent; unset disables)")
    parser.add_argument("--quiet", "-quiet", "-q", action="store_true",
                        help="Suppress the banner and request log")
    parser.add_argument("--events", "-events", action="store_true",
                        help="Record run telemetry; each run dir gets "
                             "trace.json + metrics.json with the serve-side "
                             "spans (queue_wait/admit) and instants "
                             "(cache_hit/coalesced)")
    ns = parser.parse_args(argv)

    if not ns.models:
        raise CLIError("--models flag is required")
    models = [m.strip() for m in ns.models.split(",") if m.strip()]
    if not models:
        raise CLIError("--models flag is required")
    max_batch = ns.max_batch if ns.max_batch is not None else _env_max_batch()
    if max_batch < 1:
        raise CLIError("--max-batch must be >= 1")
    if ns.max_concurrency is not None and ns.max_concurrency < 1:
        raise CLIError("--max-concurrency must be >= 1")
    if ns.queue_depth < 0:
        raise CLIError("--queue-depth must be >= 0")
    if ns.timeout <= 0:
        raise CLIError("--timeout must be > 0")
    if ns.cache_size < 0:
        raise CLIError("--cache-size must be >= 0")
    return ServeConfig(
        models=models,
        judge=ns.judge,
        host=ns.host,
        port=ns.port,
        timeout=float(ns.timeout),
        max_tokens=ns.max_tokens,
        system=ns.system,
        data_dir=ns.data_dir,
        no_save=ns.no_save,
        max_batch=max_batch,
        max_concurrency=ns.max_concurrency,
        queue_depth=ns.queue_depth,
        cache_size=ns.cache_size,
        cache_ttl=ns.cache_ttl,
        quiet=ns.quiet,
        events=ns.events,
        prefill_budget=ns.prefill_budget,
        judge_overlap=ns.judge_overlap,
        announce=ns.announce or knobs.get_str("LLMC_FLEET_ANNOUNCE"),
        draft=ns.draft,
        spec_k=ns.spec_k,
        no_live=ns.no_live,
        blackbox_dir=ns.blackbox_dir,
        slo_ttft_p99=ns.slo_ttft_p99,
        disagg=ns.disagg or knobs.get_bool("LLMC_DISAGG"),
    )


def _tpu_multiplicity(models: list[str], judge: str) -> int:
    """Peak concurrent streams one tpu preset sees from ONE run.

    A preset asked for N times in the panel contributes N concurrent
    streams; a judge sharing a panel preset can overlap another run's
    panel query on that preset, so it counts too."""
    from llm_consensus_tpu.providers.tpu import SCHEME, parse_model_name

    counts: dict[str, int] = {}
    for m in models + [judge]:
        if m.startswith(SCHEME):
            preset = parse_model_name(m)
            counts[preset] = counts.get(preset, 0) + 1
    return max(counts.values(), default=0)


def resolve_concurrency(cfg: ServeConfig) -> int:
    """Derive (or validate) the admission cap against batcher capacity."""
    from llm_consensus_tpu.cli.main import CLIError

    mult = _tpu_multiplicity(cfg.models, cfg.judge)
    if cfg.max_concurrency is None:
        if mult == 0:
            return DEFAULT_HTTP_CONCURRENCY  # HTTP-only: no device budget
        return max(1, cfg.max_batch // mult)
    if mult and cfg.max_concurrency * mult > cfg.max_batch:
        raise CLIError(
            f"--max-concurrency {cfg.max_concurrency} oversubscribes the "
            f"continuous batcher: the panel/judge put up to {mult} "
            f"concurrent stream(s) per tpu preset per run, needing "
            f"{cfg.max_concurrency * mult} slots > --max-batch "
            f"{cfg.max_batch}; raise --max-batch or lower --max-concurrency"
        )
    return cfg.max_concurrency


def serve_main(
    argv: list[str],
    *,
    stdout: TextIO,
    stderr: TextIO,
    install_signal_handlers: bool = True,
    shutdown: Optional[threading.Event] = None,
) -> int:
    """The ``serve`` subcommand body; returns the process exit code.

    ``shutdown`` is the stop signal (tests set it; production wires
    SIGTERM/SIGINT to it)."""
    from llm_consensus_tpu import obs, serve
    from llm_consensus_tpu.cli.main import CLIError, create_provider, init_registry

    cfg = parse_serve_args(argv)
    max_concurrency = resolve_concurrency(cfg)

    if cfg.judge_overlap:
        # The scheduler's per-request overlap shim reads the env gate;
        # setting it here makes the flag and LLMC_JUDGE_OVERLAP=1
        # equivalent for the server's lifetime.
        os.environ["LLMC_JUDGE_OVERLAP"] = "1"

    if cfg.events and obs.recorder() is None:
        # Before any provider/engine exists — consumers bind at
        # construction (the obs/ zero-cost pattern).
        obs.install(obs.Recorder(max_events=obs.resolve_max_events()))
    # Live plane knobs resolve at first bind, so set them BEFORE any
    # provider/batcher/gateway constructs (the same ordering --events
    # relies on above).
    if cfg.no_live:
        obs.live.install(None)
        obs.blackbox.install(None)
        obs.attrib.install(None)
    if cfg.blackbox_dir:
        os.environ["LLMC_BLACKBOX_DIR"] = cfg.blackbox_dir
    if cfg.draft:
        # Mirror the flag into the env (the provider gets the explicit
        # value either way) so everything that reports config — the
        # llmc_build_info feature labels foremost — sees one truth
        # whether speculation came from the flag or LLMC_DRAFT.
        os.environ["LLMC_DRAFT"] = cfg.draft
    if cfg.slo_ttft_p99 is not None:
        os.environ["LLMC_SLO_TTFT_P99_S"] = str(cfg.slo_ttft_p99)
    if cfg.disagg:
        # Mirror into the env (like --draft) so config reporters see one
        # truth, and enable the paged KV pool — the pool arena IS the
        # cross-mesh handoff channel, so disaggregation requires it.
        os.environ["LLMC_DISAGG"] = "1"
        os.environ.setdefault("LLMC_KV_POOL", "1")

    # One provider instance for every tpu: model, sized to --max-batch —
    # the server owns its engines, so the shared-singleton indirection
    # the one-shot CLI uses is unnecessary here.
    tpu_provider = []

    def factory(model: str):
        if model.startswith("tpu:"):
            if not tpu_provider:
                from llm_consensus_tpu.providers.tpu import TPUProvider

                provider = TPUProvider(
                    batch_streams=cfg.max_batch,
                    prefill_budget=cfg.prefill_budget,
                    draft=cfg.draft or None,
                    disagg=cfg.disagg or None,
                )
                if cfg.spec_k is not None:
                    # Applies before any engine/batcher exists, so every
                    # pool this server builds compiles with the flag's k.
                    # set_spec_k, not set_draft: --spec-k without --draft
                    # must keep an env-configured LLMC_DRAFT map.
                    provider.set_spec_k(cfg.spec_k)
                tpu_provider.append(provider)
            return tpu_provider[0]
        return create_provider(model)

    registry = init_registry(cfg.models, cfg.judge, factory)
    seen: set = set()
    for model in registry.models():
        provider = registry.get(model)
        if id(provider) in seen:
            continue
        seen.add(id(provider))
        try:
            provider.prepare(cfg.models, cfg.judge)
        except Exception as err:
            raise CLIError(f"planning device placement: {err}") from err

    log = None
    if not cfg.quiet:
        log = lambda msg: stderr.write(msg + "\n")  # noqa: E731
    gateway = serve.build_gateway(
        registry,
        cfg.models,
        cfg.judge,
        system=cfg.system or None,
        max_tokens=cfg.max_tokens,
        timeout=cfg.timeout,
        max_concurrency=max_concurrency,
        max_queue=cfg.queue_depth,
        cache_size=cfg.cache_size,
        cache_ttl_s=cfg.cache_ttl,
        data_dir=cfg.data_dir,
        save=not cfg.no_save,
        host=cfg.host,
        port=cfg.port,
        log=log,
    )
    try:
        host, port = gateway.start()
    except OSError as err:
        raise CLIError(
            f"binding {cfg.host}:{cfg.port}: {err}"
        ) from err
    if not cfg.quiet:
        ui.print_serve_banner(
            stderr, host, port, cfg.models, cfg.judge,
            max_concurrency=max_concurrency, max_batch=cfg.max_batch,
        )
    if cfg.announce:
        # Fleet membership: heartbeat-register with the router so it can
        # place requests here without static --replica config.
        gateway.announce(cfg.announce)
        if not cfg.quiet:
            stderr.write(f"announcing to fleet router {cfg.announce}\n")

    stop = shutdown if shutdown is not None else sanitizer.make_event("cli.shutdown")
    if install_signal_handlers:
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                signal.signal(sig, lambda *_: stop.set())
            except ValueError:
                break  # not the main thread (tests)
        if hasattr(signal, "SIGQUIT"):
            # kill -QUIT <pid> = on-demand flight-recorder dump (same
            # rate-limited path as POST /debugz/blackbox) — the
            # "something is weird RIGHT NOW" snapshot, no restart needed.
            try:
                signal.signal(
                    signal.SIGQUIT,
                    lambda *_: gateway.debug_blackbox("sigquit"),
                )
            except ValueError:
                pass
    stop.wait()
    if not cfg.quiet:
        ui.print_phase(stderr, "Draining: finishing in-flight runs...")
    drained = gateway.close(drain=True, timeout=max(cfg.timeout, 1.0))
    if not cfg.quiet:
        if drained:
            ui.print_success(stderr, "Drained cleanly; all runs flushed")
        else:
            ui.print_error(stderr, "Drain timed out; stragglers cancelled")
    return 0 if drained else 1
