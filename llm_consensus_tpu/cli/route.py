"""``llm-consensus route`` — the fleet router in front of N gateways.

Where ``serve`` makes ONE process resident, ``route`` fronts many of
them: it places each request on its home replica by consistent hash of
the coalescing cache key (identical concurrent requests collapse to one
execution fleet-wide), tracks replica health with hysteresis off their
``/healthz`` + ``/statsz``, fails streams over to a healthy replica when
one dies mid-decode (emitted-prefix replay — the client sees a pause,
never a dropped or duplicated chunk), and — with ``--spillover-models``
— degrades to the remote-API providers when the whole TPU fleet is dead
or saturated, tagging the response ``degraded: remote``.

Replicas arrive two ways: statically via ``--replica`` (repeatable or
comma-separated), and dynamically — gateways started with
``serve --announce http://router:port`` register themselves by periodic
heartbeat and age out when they stop beating.
"""

from __future__ import annotations

import argparse
import signal
import threading

from llm_consensus_tpu.analysis import sanitizer
from typing import Optional, TextIO


def parse_route_args(argv: list[str]):
    from llm_consensus_tpu.cli.main import CLIError

    parser = argparse.ArgumentParser(
        prog="llm-consensus route",
        description="Route consensus requests over a fleet of gateways.",
    )
    parser.add_argument("--replica", "-replica", action="append", default=[],
                        metavar="URL",
                        help="Gateway replica base URL (repeat or "
                             "comma-separate); more may join via "
                             "serve --announce heartbeats")
    parser.add_argument("--host", "-host", default="127.0.0.1",
                        help="Bind address (default 127.0.0.1)")
    parser.add_argument("--port", "-port", type=int, default=8081,
                        help="Bind port (0 = OS-assigned)")
    parser.add_argument("--poll-s", "-poll-s", type=float, default=None,
                        help="Replica health-poll interval in seconds "
                             "(default LLMC_FLEET_POLL_S or 2.0)")
    parser.add_argument("--saturation", "-saturation", type=float,
                        default=None,
                        help="load_score at/above which placement "
                             "overflows to the next ring replica "
                             "(default LLMC_FLEET_SATURATION or 0.85)")
    parser.add_argument("--spillover", "-spillover", default=None,
                        choices=["off", "saturated"],
                        help="Remote-API degradation policy: 'saturated' "
                             "spills eligible requests when no live "
                             "replica can take them (default when "
                             "--spillover-models is set; else 'off')")
    parser.add_argument("--spillover-models", "-spillover-models",
                        default="", metavar="LIST",
                        help="Comma-separated remote panel models for the "
                             "spillover lane (OpenAI/Anthropic/Google "
                             "catalog names)")
    parser.add_argument("--spillover-judge", "-spillover-judge", default="",
                        help="Remote judge model for the spillover lane "
                             "(defaults to the CLI's default judge)")
    parser.add_argument("--data-dir", "-data-dir", default="data",
                        help="Run-dir root for spillover executions")
    parser.add_argument("--save", "-save", action="store_true",
                        help="Persist spillover run dirs (off by default: "
                             "the replicas own persistence)")
    parser.add_argument("--min-replicas", "-min-replicas", type=int,
                        default=None,
                        help="Elastic floor: the scale controller never "
                             "shrinks the serving pool below this "
                             "(default LLMC_ELASTIC_MIN_REPLICAS or 1)")
    parser.add_argument("--max-replicas", "-max-replicas", type=int,
                        default=None,
                        help="Elastic ceiling: the scale controller never "
                             "grows the serving pool above this "
                             "(default LLMC_ELASTIC_MAX_REPLICAS or 8)")
    parser.add_argument("--quiet", "-quiet", "-q", action="store_true",
                        help="Suppress the banner and request log")
    parser.add_argument("--events", "-events", action="store_true",
                        help="Record router telemetry (route/poll spans, "
                             "fleet.* counters) into the process recorder")
    ns = parser.parse_args(argv)

    replicas = [
        u.strip()
        for arg in ns.replica for u in arg.split(",") if u.strip()
    ]
    for url in replicas:
        if not url.startswith(("http://", "https://")):
            raise CLIError(
                f"--replica {url!r}: expected an http(s) base URL"
            )
    spill_models = [
        m.strip() for m in ns.spillover_models.split(",") if m.strip()
    ]
    policy = ns.spillover
    if policy is None:
        policy = "saturated" if spill_models else "off"
    if policy != "off" and not spill_models:
        raise CLIError(
            "--spillover requires --spillover-models (the remote panel)"
        )
    return ns, replicas, spill_models, policy


def route_main(
    argv: list[str],
    *,
    stdout: TextIO,
    stderr: TextIO,
    install_signal_handlers: bool = True,
    shutdown: Optional[threading.Event] = None,
) -> int:
    """The ``route`` subcommand body; returns the process exit code."""
    from llm_consensus_tpu import obs, serve
    from llm_consensus_tpu.cli.main import DEFAULT_JUDGE, CLIError
    from llm_consensus_tpu.serve.router import SpilloverPolicy

    ns, replicas, spill_models, policy = parse_route_args(argv)

    if ns.events and obs.recorder() is None:
        obs.install(obs.Recorder(max_events=obs.resolve_max_events()))

    spill_registry = None
    spill_judge = None
    if spill_models:
        from llm_consensus_tpu.providers.registry import remote_registry

        spill_judge = ns.spillover_judge or DEFAULT_JUDGE
        try:
            spill_registry = remote_registry(spill_models, spill_judge)
        except (ValueError, RuntimeError) as err:
            # ValueError: unknown catalog name; RuntimeError: a provider
            # refusing to build (missing API key) — both are user config.
            raise CLIError(f"spillover panel: {err}") from err

    log = None
    if not ns.quiet:
        log = lambda msg: stderr.write(msg + "\n")  # noqa: E731
    router = serve.build_router(
        replicas,
        poll_s=ns.poll_s,
        saturation=ns.saturation,
        spillover_registry=spill_registry,
        spillover_models=spill_models,
        spillover_judge=spill_judge,
        spillover_policy=SpilloverPolicy(policy),
        min_replicas=ns.min_replicas,
        max_replicas=ns.max_replicas,
        data_dir=ns.data_dir,
        save=ns.save,
        host=ns.host,
        port=ns.port,
        log=log,
    )
    try:
        host, port = router.start()
    except OSError as err:
        raise CLIError(f"binding {ns.host}:{ns.port}: {err}") from err
    if not ns.quiet:
        stderr.write(
            f"fleet router on http://{host}:{port} — "
            f"{len(replicas)} static replica(s), spillover={policy}"
            + (f" via {','.join(spill_models)}" if spill_models else "")
            + "\n"
        )

    stop = shutdown if shutdown is not None else sanitizer.make_event("cli.shutdown")
    if install_signal_handlers:
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                signal.signal(sig, lambda *_: stop.set())
            except ValueError:
                break  # not the main thread (tests)
    stop.wait()
    router.close()
    return 0
