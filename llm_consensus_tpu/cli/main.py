"""CLI entry point — flag-for-flag parity with the reference binary.

Parity: /root/reference/cmd/llm-consensus/main.go. Preserved behaviors:

  * Flag set (main.go:312-322): --models --judge --file --output --data-dir
    --timeout --quiet/-q --json --no-save --version (single-dash Go-style
    spellings also accepted).
  * Prompt precedence: positional args > --file > piped stdin (main.go:363-393).
  * Registry init: one provider per unique model, judge auto-added
    (main.go:395-415); unknown model errors list the available set.
  * Run lifecycle: signal-cancelled context (main.go:90-91), progress UI on
    stderr when it is a TTY and not quiet/json, best-effort fan-out, judge
    synthesis with its own progress, auto-save to data/<run-id>/, output
    routing matrix file | --json stdout | pretty TTY | JSON stdout
    (main.go:187-273).
  * Errors print ``error: ...`` to stderr and exit 1 (main.go:76-81).

New in the TPU build: ``tpu:<model>`` model names route to the on-device
engine provider; everything else resolves through the known-models table
like the reference.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from functools import partial
from dataclasses import dataclass, field as dataclasses_field, replace as dataclasses_replace
from typing import Callable, Optional, TextIO

from llm_consensus_tpu import output as output_mod
from llm_consensus_tpu import ui
from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.consensus import (
    Judge,
    grade_confidence,
    score_agreement,
    render_critique_prompt,
    render_refine_prompt,
    render_vote_prompt,
    tally_votes,
)
from llm_consensus_tpu.output.persist import reserve_run_dir, save_aux_files
from llm_consensus_tpu.providers import Provider, Registry
from llm_consensus_tpu.runner import Callbacks, Runner
from llm_consensus_tpu.utils.context import Context
from llm_consensus_tpu.version import version_string
from llm_consensus_tpu.utils import knobs

DEFAULT_JUDGE = "gpt-5.2-pro-2025-12-11"  # main.go:34
DEFAULT_TIMEOUT_S = 120  # main.go:35

# Known models → provider kind (main.go:49-61). The catalog itself lives
# in providers/registry.py (REMOTE_MODELS) so the router's spillover lane
# can build remote providers without importing the CLI; this alias keeps
# the CLI's historical name.
from llm_consensus_tpu.providers.registry import REMOTE_MODELS as KNOWN_MODELS

ProviderFactory = Callable[[str], Provider]


@dataclass
class Config:
    """Parsed CLI configuration (main.go:63-74)."""

    models: list[str]
    judge: str = DEFAULT_JUDGE
    file: str = ""
    output: str = ""
    data_dir: str = "data"
    timeout: float = DEFAULT_TIMEOUT_S
    prompt: str = ""
    quiet: bool = False
    json: bool = False
    no_save: bool = False
    max_tokens: "Optional[int]" = None
    trace: str = ""
    rounds: int = 1          # multi-round consensus (TPU-build extension)
    vote: bool = False       # voting mode (TPU-build extension)
    options: list[str] = dataclasses_field(default_factory=list)
    continue_run: str = ""   # run-id to continue from (TPU-build extension)
    system: str = ""         # system prompt for panel models (extension)
    interactive: bool = False  # REPL mode (extension)
    confidence: bool = False  # judge-graded consensus confidence (extension)
    draft: str = ""          # speculative-decoding draft spec (extension)
    spec_k: "Optional[int]" = None  # draft-length ceiling (extension)
    events: bool = False     # run telemetry → trace.json/metrics.json (ext.)
    profile: bool = False    # bounded deep-profiler window (extension)
    prefill_budget: "Optional[int]" = None  # interleaved admission (ext.)
    judge_overlap: bool = False  # incremental judge prefill (extension)
    resume: str = ""         # run-id to resume after a crash (extension)
    priority: str = ""       # panel priority class (pressure/, extension)


class CLIError(Exception):
    """User-facing CLI error → ``error: ...`` + exit 1."""


def create_provider(model: str, draft: Optional[str] = None,
                    spec_k: Optional[int] = None) -> Provider:
    """Resolve a model name to its provider (main.go:417-438).

    ``tpu:<name>`` → on-device engine; otherwise the known-models table.
    ``draft`` / ``spec_k`` (the ``--draft`` / ``--spec-k`` flags)
    configure speculative decoding on the shared tpu provider — plumbed
    as arguments rather than env vars so one run's flags can't leak into
    the next in-process run.
    """
    if model.startswith("tpu:"):
        try:
            from llm_consensus_tpu.providers.tpu import TPUProvider
        except ImportError as err:
            raise CLIError(f"tpu provider unavailable: {err}") from err
        provider = TPUProvider.shared()
        if draft is not None:
            provider.set_draft(draft, k=spec_k)
        return provider
    from llm_consensus_tpu.providers.registry import create_remote_provider

    try:
        return create_remote_provider(model)
    except ValueError:
        available = sorted(KNOWN_MODELS) + ["tpu:<model>"]
        raise CLIError(
            f"unknown model {model!r}; available models: {available}"
        ) from None


def init_registry(
    models: list[str], judge: Optional[str], factory: ProviderFactory
) -> Registry:
    """One provider per unique model, judge included (main.go:395-415).

    ``judge=None`` (voting mode) registers the panel only."""
    registry = Registry()
    for model in dict.fromkeys(models + ([judge] if judge else [])):
        try:
            provider = factory(model)
        except CLIError:
            raise
        except Exception as err:
            raise CLIError(f"initializing provider for {model}: {err}") from err
        registry.register(model, provider)
    return registry


def get_prompt(args: list[str], file: str, stdin: TextIO) -> str:
    """Prompt precedence: positional > --file > piped stdin (main.go:363-393)."""
    if args:
        return " ".join(args)
    if file:
        try:
            with open(file, "r", encoding="utf-8") as f:
                return f.read().strip()
        except OSError as err:
            raise CLIError(f"reading prompt file: {err}") from err
    if stdin is not None and not ui.is_terminal(stdin):
        return stdin.read().rstrip("\n")
    raise CLIError("no prompt provided: use positional argument, --file, or pipe to stdin")


# Config-file keys that set flag defaults (CLI flags always win).
_CONFIG_FLAG_KEYS = frozenset({
    "models", "judge", "timeout", "data_dir", "max_tokens", "system",
    "rounds", "confidence", "draft",
})


def load_config_file() -> tuple[dict, str]:
    """Persistent configuration (reference roadmap §7.1): defaults and
    model aliases from ``.llm-consensus.json`` in the working directory,
    else ``~/.llm-consensus.json``. ``LLMC_CONFIG=<path>`` overrides the
    search; ``LLMC_CONFIG=0`` disables. Returns ({}, "") when none found.
    """
    env = knobs.get_str("LLMC_CONFIG")
    if env == "0":
        return {}, ""
    if env:
        path = os.path.expanduser(env)
        if not os.path.exists(path):
            raise CLIError(f"LLMC_CONFIG points to a missing file: {path}")
        candidates = [path]
    else:
        candidates = [
            ".llm-consensus.json",
            os.path.expanduser("~/.llm-consensus.json"),
        ]
    for path in candidates:
        if not os.path.exists(path):
            continue
        try:
            with open(path, "r", encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as err:
            raise CLIError(f"reading config file {path}: {err}") from err
        if not isinstance(data, dict):
            raise CLIError(f"config file {path}: expected a JSON object")
        unknown = set(data) - _CONFIG_FLAG_KEYS - {"aliases"}
        if unknown:
            raise CLIError(
                f"config file {path}: unknown keys {sorted(unknown)} "
                f"(known: {sorted(_CONFIG_FLAG_KEYS | {'aliases'})})"
            )
        _validate_config_types(data, path)
        return data, path
    return {}, ""


def _validate_config_types(data: dict, path: str) -> None:
    """Reject wrong-typed config values with a CLIError — set_defaults()
    bypasses argparse type conversion, so raw JSON types flow straight
    into the run otherwise."""
    def fail(key, expected):
        raise CLIError(
            f"config file {path}: {key!r} must be {expected}, "
            f"got {type(data[key]).__name__}"
        )

    for key in ("models", "judge", "system", "data_dir"):
        if key in data and not isinstance(data[key], str):
            fail(key, "a string")
    for key in ("timeout", "max_tokens"):
        if key in data and (
            isinstance(data[key], bool) or not isinstance(data[key], (int, float))
        ):
            fail(key, "a number")
    if "rounds" in data and (
        isinstance(data["rounds"], bool) or not isinstance(data["rounds"], int)
    ):
        fail("rounds", "an integer")
    if "confidence" in data and not isinstance(data["confidence"], bool):
        fail("confidence", "a boolean")
    aliases = data.get("aliases")
    if aliases is not None:
        if not isinstance(aliases, dict) or not all(
            isinstance(k, str) and isinstance(v, str) for k, v in aliases.items()
        ):
            raise CLIError(
                f"config file {path}: 'aliases' must map alias names to "
                f"comma-separated model strings"
            )


def expand_aliases(models: list[str], aliases: dict) -> list[str]:
    """``@alias`` entries → their comma-separated model lists (reference
    roadmap §1.2). Duplicates are preserved — an explicit repeated model
    has always meant two queries, and alias overlap follows the same
    rule."""
    out: list[str] = []
    for m in models:
        if m.startswith("@"):
            if m not in aliases:
                raise CLIError(
                    f"unknown model alias {m!r}; defined: {sorted(aliases)}"
                )
            out.extend(x.strip() for x in aliases[m].split(",") if x.strip())
        else:
            out.append(m)
    return out


def parse_args(argv: list[str], stdin: TextIO, stdout: TextIO) -> Optional[Config]:
    """Parse flags; returns None when --version handled (main.go:298-361)."""
    parser = argparse.ArgumentParser(
        prog="llm-consensus",
        description="Query multiple LLMs in parallel and synthesize a consensus answer.",
        add_help=True,
    )
    parser.add_argument("--models", "-models", default="", metavar="LIST",
                        help="Comma-separated list of models to query (required)")
    parser.add_argument("--judge", "-judge", default=DEFAULT_JUDGE,
                        help="Model to use for consensus synthesis")
    parser.add_argument("--file", "-file", default="", help="Read prompt from file")
    parser.add_argument("--output", "-output", default="",
                        help="Write JSON output to specific file (overrides auto-save)")
    parser.add_argument("--data-dir", "-data-dir", default="data",
                        help="Directory for auto-saved runs")
    parser.add_argument("--timeout", "-timeout", type=int, default=DEFAULT_TIMEOUT_S,
                        help="Per-model timeout in seconds")
    parser.add_argument("--max-tokens", "-max-tokens", type=int, default=None,
                        help="Max tokens generated per model (tpu models; TPU-build extension)")
    parser.add_argument("--trace", "-trace", default="", metavar="DIR",
                        help="Write a jax.profiler trace of the run to DIR (TPU-build extension)")
    parser.add_argument("--events", "-events", action="store_true",
                        help="Record the run's host telemetry timeline "
                             "(spans/counters/instants across engine, "
                             "batcher, runner, exchange); persisted as "
                             "trace.json (Perfetto-loadable) + metrics.json "
                             "in the run dir. LLMC_EVENTS=1 is equivalent "
                             "(TPU-build extension)")
    parser.add_argument("--profile", "-profile", action="store_true",
                        help="Arm one bounded deep-profiling window "
                             "(obs/profiler) around the run — the same "
                             "jax.profiler artifact POST /debugz/profile "
                             "produces, capped at LLMC_PROFILE_MAX_S and "
                             "closed when the run finishes. Unlike "
                             "--trace it is rate-limited and lands in an "
                             "atomic artifact dir under LLMC_PROFILE_DIR "
                             "(TPU-build extension)")
    parser.add_argument("--rounds", "-rounds", type=int, default=1,
                        help="Consensus rounds: after each synthesis the panel "
                             "critiques the draft and the judge refines it "
                             "(TPU-build extension)")
    parser.add_argument("--vote", "-vote", action="store_true",
                        help="Voting mode: panel picks one of --options; no judge "
                             "(TPU-build extension)")
    parser.add_argument("--options", "-options", default="", metavar="LIST",
                        help="Comma-separated options for --vote")
    parser.add_argument("--continue", "-continue", dest="continue_run",
                        default="", metavar="RUN_ID",
                        help="Continue the conversation from a saved run in "
                             "--data-dir (TPU-build extension)")
    parser.add_argument("--resume", "-resume", default="", metavar="RUN_ID",
                        help="Finish a crashed run in --data-dir: reuse the "
                             "panel answers its journal already completed "
                             "(data/<run-id>/panel/), rerun only the "
                             "missing/failed models, then the judge "
                             "(TPU-build extension)")
    parser.add_argument("--system", "-system", default="",
                        help="System prompt for every panel model "
                             "(TPU-build extension)")
    parser.add_argument("--system-file", "-system-file", default="",
                        metavar="PATH",
                        help="Read the system prompt from a file")
    parser.add_argument("--interactive", "-interactive", "-i",
                        action="store_true",
                        help="REPL mode: one consensus query per line, "
                             "conversation carried across queries "
                             "(TPU-build extension)")
    parser.add_argument("--confidence", "-confidence", action="store_true",
                        help="After synthesis, the judge grades its "
                             "confidence in the consensus (0-100) and lists "
                             "controversy points (TPU-build extension)")
    parser.add_argument("--spec-k", "-spec-k", type=int, default=None,
                        metavar="K",
                        help="Speculative draft-length ceiling per round "
                             "(default LLMC_SPEC_K or 4); adaptive k walks "
                             "a pow2 ladder below it")
    parser.add_argument("--draft", "-draft", default="", metavar="SPEC",
                        help="Speculative decoding for tpu models: 'lookup' "
                             "(prompt-lookup n-grams, zero draft cost, "
                             "composes with --max-batch pools), a draft "
                             "preset for all targets (e.g. consensus-1b) or "
                             "target=draft pairs (a=b,c=d). Greedy output "
                             "is token-exact; the draft only changes speed")
    parser.add_argument("--prefill-budget", "-prefill-budget", type=int,
                        default=None, metavar="TOKENS",
                        help="Interleaved admission prefill for tpu "
                             "continuous batching: dispatch at most this "
                             "many prompt tokens of a new stream's prefill "
                             "between decode chunks, so resident streams "
                             "keep decoding during admission. 0/unset = "
                             "classic stall-the-pool admission; "
                             "LLMC_PREFILL_BUDGET is equivalent "
                             "(TPU-build extension)")
    parser.add_argument("--judge-overlap", "-judge-overlap",
                        action="store_true",
                        help="Prefill the judge prompt incrementally as "
                             "panel answers arrive (tpu judges), cutting "
                             "judge time-to-first-token by nearly the "
                             "whole prompt prefill. LLMC_JUDGE_OVERLAP=1 "
                             "is equivalent (TPU-build extension)")
    parser.add_argument("--priority", "-priority", default="",
                        metavar="CLASS",
                        help="Priority class for the panel queries "
                             "(high/normal/low or 0-2): orders "
                             "continuous-batcher admission and selects "
                             "preemption victims on shared engines. The "
                             "judge always outranks the panel by one "
                             "class. Default: normal")
    parser.add_argument("--quiet", "-quiet", "-q", action="store_true",
                        help="Suppress progress output")
    parser.add_argument("--json", "-json", action="store_true",
                        help="Output JSON to stdout (no interactive display, no auto-save)")
    parser.add_argument("--no-save", "-no-save", action="store_true",
                        help="Don't auto-save results to data directory")
    parser.add_argument("--version", "-version", action="store_true",
                        help="Print version information and exit")
    parser.add_argument("prompt", nargs="*", help="The prompt (or use --file / stdin)")

    # Config-file values become flag defaults, so explicit flags always
    # win: CLI > config file > built-in default. --version/--help must
    # work even with a broken config (how else would one debug it?), so
    # those invocations skip the config entirely.
    skip_config = any(
        a in ("--version", "-version", "--help", "-h") for a in argv
    )
    config, _config_path = ({}, "") if skip_config else load_config_file()
    flag_defaults = {k: v for k, v in config.items() if k in _CONFIG_FLAG_KEYS}
    if flag_defaults:
        parser.set_defaults(**flag_defaults)

    ns = parser.parse_args(argv)

    if ns.version:
        stdout.write(version_string() + "\n")
        return None

    if not ns.models and not ns.resume:
        raise CLIError("--models flag is required")

    options = [o.strip() for o in ns.options.split(",") if o.strip()]
    if ns.vote and len(options) < 2:
        raise CLIError("--vote requires --options with at least two choices")
    if options and not ns.vote:
        raise CLIError("--options only applies with --vote")
    if ns.rounds < 1:
        raise CLIError("--rounds must be >= 1")
    if ns.vote and ns.rounds != 1:
        raise CLIError("--vote and --rounds are mutually exclusive")
    if ns.vote and ns.confidence:
        raise CLIError(
            "--vote and --confidence are mutually exclusive (voting mode "
            "has no judge to grade the consensus)"
        )

    system = ns.system
    if ns.system_file:
        if system:
            raise CLIError("--system and --system-file are mutually exclusive")
        try:
            with open(ns.system_file, "r", encoding="utf-8") as f:
                system = f.read().strip()
        except OSError as err:
            raise CLIError(f"reading system prompt file: {err}") from err

    models = expand_aliases(
        [m.strip() for m in ns.models.split(",") if m.strip()],
        config.get("aliases", {}) or {},
    )
    judge_list = expand_aliases([ns.judge], config.get("aliases", {}) or {})
    if len(judge_list) != 1:
        raise CLIError(
            f"--judge must resolve to exactly one model, got {judge_list}"
        )
    judge = judge_list[0]
    cfg = Config(
        models=models,
        judge=judge,
        file=ns.file,
        output=ns.output,
        data_dir=ns.data_dir,
        timeout=float(ns.timeout),
        quiet=ns.quiet,
        json=ns.json,
        no_save=ns.no_save,
        max_tokens=ns.max_tokens,
        trace=ns.trace,
        rounds=ns.rounds,
        vote=ns.vote,
        options=options,
        continue_run=ns.continue_run,
        system=system,
        interactive=ns.interactive,
        confidence=ns.confidence,
        draft=ns.draft,
        spec_k=ns.spec_k,
        events=ns.events,
        profile=ns.profile,
        prefill_budget=ns.prefill_budget,
        judge_overlap=ns.judge_overlap,
        priority=ns.priority,
    )
    if cfg.priority:
        from llm_consensus_tpu.pressure import parse_priority

        try:
            parse_priority(cfg.priority)
        except ValueError as err:
            raise CLIError(str(err)) from err
    if ns.resume:
        # A resumed run's identity (prompt, panel, judge, settings) comes
        # from its manifest; flags that would change the identity — or
        # disable the persistence the resume writes into — contradict it.
        if ns.prompt or ns.file:
            raise CLIError("--resume takes the prompt from the saved run")
        if ns.interactive:
            raise CLIError("--resume and --interactive are incompatible")
        if ns.continue_run:
            raise CLIError("--resume and --continue are incompatible")
        if ns.output or ns.json or ns.no_save:
            raise CLIError(
                "--resume writes into the saved run directory; it is "
                "incompatible with --output/--json/--no-save"
            )
        # Identity-changing flags are silently overridden by the
        # manifest — reject them instead of discarding the user's
        # intent. Checked against argv (not parsed values) so config-
        # file defaults don't false-positive.
        identity_flags = (
            "--models", "-models", "--judge", "-judge", "--system",
            "-system", "--system-file", "-system-file", "--max-tokens",
            "-max-tokens", "--vote", "-vote", "--options", "-options",
            "--rounds", "-rounds", "--confidence", "-confidence",
        )
        clashing = sorted({
            f for f in identity_flags
            for a in argv if a == f or a.startswith(f + "=")
        })
        if clashing:
            raise CLIError(
                f"--resume takes {', '.join(clashing)} from the saved "
                "run's manifest; drop the flag(s) or start a fresh run"
            )
        cfg.resume = ns.resume
        return cfg
    if ns.interactive:
        if ns.prompt:
            raise CLIError("--interactive takes queries from stdin, not arguments")
        if ns.file:
            raise CLIError("--interactive takes queries from stdin, not --file")
        if ns.output:
            raise CLIError(
                "--interactive and --output are incompatible (each query "
                "would overwrite the file); use the auto-saved run dirs"
            )
        return cfg
    cfg.prompt = get_prompt(ns.prompt, ns.file, stdin)
    return cfg


def load_history(data_dir: str, run_id: str) -> list[dict]:
    """Conversation history for ``--continue`` (reference roadmap §3.1).

    Returns the prior run's history plus its own exchange, oldest first."""
    path = os.path.join(data_dir, run_id, "result.json")
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError) as err:
        raise CLIError(f"loading run {run_id!r}: {err}") from err
    if not isinstance(data, dict) or "prompt" not in data or "consensus" not in data:
        raise CLIError(f"run {run_id!r} has no usable result.json")
    history = [
        h for h in data.get("history", [])
        if isinstance(h, dict) and "prompt" in h and "consensus" in h
    ]
    history.append({"prompt": data["prompt"], "consensus": data["consensus"]})
    return history


def _slug(model: str) -> str:
    """Filesystem-safe model-name slug for panel journal files."""
    return "".join(c if c.isalnum() or c in "-_." else "_" for c in model)


def write_run_manifest(run_dir: str, cfg: Config, history: list[dict],
                       warn=None) -> None:
    """Persist the run's identity BEFORE the panel fan-out, so a crashed
    process leaves enough in ``data/<run-id>/`` for ``--resume`` to
    finish the run: prompt, panel, judge, and every setting that changes
    what the models see."""
    from llm_consensus_tpu.output.persist import save_file

    manifest = {
        "prompt": cfg.prompt,
        "models": list(cfg.models),
        "judge": cfg.judge,
        "system": cfg.system,
        "max_tokens": cfg.max_tokens,
        "timeout": cfg.timeout,
        "rounds": cfg.rounds,
        "vote": cfg.vote,
        "options": list(cfg.options),
        "confidence": cfg.confidence,
        "history": history,
    }
    save_file(run_dir, "run.json", json.dumps(manifest, indent=2), warn=warn)


def load_resume_manifest(data_dir: str, run_id: str) -> dict:
    """The saved run's manifest, or a CLIError that says what's wrong."""
    run_dir = os.path.join(data_dir, run_id)
    path = os.path.join(run_dir, "run.json")
    if os.path.exists(os.path.join(run_dir, "result.json")):
        raise CLIError(
            f"run {run_id!r} already completed (result.json exists); "
            "use --continue to build on it"
        )
    try:
        with open(path, "r", encoding="utf-8") as f:
            manifest = json.load(f)
    except (OSError, ValueError) as err:
        raise CLIError(
            f"resuming run {run_id!r}: no usable run.json ({err}); only "
            "runs started by this version journal their manifest"
        ) from err
    if not isinstance(manifest, dict) or not manifest.get("models"):
        raise CLIError(f"resuming run {run_id!r}: run.json has no panel")
    return manifest


def load_panel_journal(run_dir: str) -> list:
    """Completed panel answers journaled under ``<run_dir>/panel/``,
    in journal order. Torn or unparseable files are skipped — their
    models simply rerun, which is the safe direction."""
    from llm_consensus_tpu.providers import Response

    panel_dir = os.path.join(run_dir, "panel")
    if not os.path.isdir(panel_dir):
        return []
    out = []
    for name in sorted(os.listdir(panel_dir)):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(panel_dir, name), encoding="utf-8") as f:
                doc = json.load(f)
            out.append(Response(
                model=doc["model"],
                content=doc["content"],
                provider=doc.get("provider", ""),
                latency_ms=doc.get("latency_ms", 0.0),
                truncated=doc.get("truncated", False),
                tokens=doc.get("tokens"),
                tokens_per_sec=doc.get("tokens_per_sec"),
                mfu=doc.get("mfu"),
                mbu=doc.get("mbu"),
            ))
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def render_conversation(history: list[dict], prompt: str) -> str:
    """Fold earlier exchanges into the prompt the models see."""
    parts = ["Earlier exchanges in this conversation:"]
    for h in history:
        parts.append(f"\n[User]\n{h['prompt']}\n\n[Answer]\n{h['consensus']}")
    parts.append(f"\nCurrent follow-up prompt:\n{prompt}")
    return "\n".join(parts)


def run(
    cfg: Config,
    ctx: Context,
    *,
    factory: ProviderFactory = create_provider,
    stdout: TextIO,
    stderr: TextIO,
    stdin: Optional[TextIO] = None,
) -> None:
    """Full run lifecycle (main.go:83-276); ``--trace`` wraps it in a
    jax.profiler trace (device + host timelines for every phase)."""
    from llm_consensus_tpu import obs

    if cfg.events:
        # Enable the run telemetry recorder BEFORE any provider, engine,
        # runner, or batcher exists: consumers bind it at construction
        # time (the obs/faults zero-cost pattern), so a late install
        # would record nothing. LLMC_EVENTS=1 resolves equivalently.
        if obs.recorder() is None:
            from llm_consensus_tpu.providers.tpu import TPUProvider

            if TPUProvider._shared is not None:
                # A warm shared provider predates this install: its
                # engines/batchers bound None at construction and will
                # not record. Say so rather than emitting a silently
                # hollow trace.
                stderr.write(
                    "warning: --events enabled after the shared tpu "
                    "provider was built; its warm engines will not "
                    "record device spans this run (use --events from "
                    "the first run of the process, or LLMC_EVENTS=1)\n"
                )
            obs.install(obs.Recorder(max_events=obs.resolve_max_events()))
    elif not knobs.get_bool("LLMC_EVENTS", False):
        # The --events install is flag-scoped: a previous run() in this
        # process must not leak its recorder into a run that didn't ask
        # for telemetry. The env remains the process-wide opt-in.
        obs.install(None)
    # A resumed run's identity comes from the saved manifest — applied
    # BEFORE the tpu-model scan below, so a resumed on-device run still
    # joins its cluster / plans its placement exactly like the original.
    resume_manifest = None
    if cfg.resume:
        resume_manifest = manifest = load_resume_manifest(
            cfg.data_dir, cfg.resume
        )
        cfg = dataclasses_replace(
            cfg,
            prompt=manifest.get("prompt", ""),
            models=list(manifest["models"]),
            judge=manifest.get("judge") or cfg.judge,
            system=manifest.get("system") or "",
            max_tokens=manifest.get("max_tokens"),
            timeout=float(manifest.get("timeout") or cfg.timeout),
            rounds=int(manifest.get("rounds") or 1),
            vote=bool(manifest.get("vote", False)),
            options=list(manifest.get("options") or []),
            confidence=bool(manifest.get("confidence", False)),
        )
    # Join the multi-host cluster first: jax.distributed.initialize must
    # run before anything initializes the JAX backend (start_trace does).
    # No-op unless LLMC_COORDINATOR/LLMC_NUM_PROCESSES or a TPU-pod env
    # says this process is part of a cluster. Voting mode never runs the
    # judge, so a tpu: judge name alone doesn't pull in the TPU stack.
    run_models = cfg.models + ([] if cfg.vote else [cfg.judge])
    if cfg.prefill_budget is not None:
        # The batcher reads LLMC_PREFILL_BUDGET at construction; setting
        # it before any provider/engine exists makes the flag and the env
        # equivalent. Batchers already warm in this process keep the
        # budget they were built with (interactive sessions: the flag
        # applies from the first query).
        os.environ["LLMC_PREFILL_BUDGET"] = str(cfg.prefill_budget)
    if factory is create_provider:
        # Thread --draft through to the tpu provider as an argument
        # UNCONDITIONALLY (an env side-channel would leak this run's
        # draft into later in-process runs — and so would skipping the
        # call when the flag is empty: the shared provider would keep a
        # previous run's draft map; set_draft('') clears it). Injected
        # test factories keep their own shape.
        factory = partial(create_provider, draft=cfg.draft, spec_k=cfg.spec_k)
    if any(m.startswith("tpu:") for m in run_models):
        from llm_consensus_tpu.parallel.distributed import initialize

        try:
            initialize()
        except Exception as err:
            raise CLIError(f"joining distributed cluster: {err}") from err
        import jax

        if jax.process_count() > 1 and cfg.interactive:
            # A REPL cannot keep N controller processes in lockstep —
            # secondary controllers have no stdin, and a diverged process
            # would deadlock the cluster inside the next collective.
            raise CLIError(
                "--interactive is not supported under multi-controller "
                "execution; pass the prompt as an argument or --file"
            )

    def body() -> None:
        if cfg.interactive:
            interactive_loop(
                cfg, ctx, factory=factory,
                stdin=stdin if stdin is not None else sys.stdin,
                stdout=stdout, stderr=stderr,
            )
        else:
            _run(cfg, ctx, factory=factory, stdout=stdout, stderr=stderr,
                 resume_manifest=resume_manifest)

    if not cfg.trace:
        if not cfg.profile:
            return body()
        # --profile: one bounded window through the deep profiler — the
        # same artifact contract as POST /debugz/profile (atomic dir,
        # duration capped at LLMC_PROFILE_MAX_S), closed early when the
        # run finishes first. Force-installed like --events: the flag is
        # an explicit ask, it overrides a disabled-by-env profiler.
        from llm_consensus_tpu.obs import profiler as profiler_mod

        prof = profiler_mod.profiler()
        if prof is None:
            prof = profiler_mod.DeepProfiler()
            profiler_mod.install(prof)
        path, status = prof.arm(prof.max_s, tag="cli")
        if status != "armed":
            stderr.write(
                f"warning: --profile window not armed ({status})\n"
            )
            return body()
        try:
            return body()
        finally:
            final = prof.stop_now() or path
            if final:
                stderr.write(f"profile artifact: {final}\n")
    try:
        import jax

        jax.profiler.start_trace(cfg.trace)
    except Exception as err:
        raise CLIError(f"starting profiler trace: {err}") from err
    try:
        return body()
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass


def _run(
    cfg: Config,
    ctx: Context,
    *,
    factory: ProviderFactory,
    stdout: TextIO,
    stderr: TextIO,
    history: "Optional[list[dict]]" = None,
    resume_manifest: "Optional[dict]" = None,
) -> output_mod.Result:
    show_ui = ui.is_terminal(stderr) and not cfg.quiet and not cfg.json
    start_time = time.monotonic()

    # Per-query telemetry reset AT ENTRY (not exit): interactive sessions
    # call _run once per query and catch CLIError to keep the session
    # alive, so an exit-side clear would be skipped on failure paths and
    # leak the failed query's events into the next query's artifacts.
    # Consumers keep their bound reference (warm engines), so the
    # recorder empties in place.
    from llm_consensus_tpu import obs as obs_mod

    recorder = obs_mod.recorder()
    if recorder is not None:
        recorder.clear()
    # Live/attrib watermarks: the no-events metrics.json below persists
    # only when THIS query grew the (process-lifetime) planes — a run
    # that observed nothing must not inherit telemetry files at all.
    # The PERSISTED content is still the cumulative process snapshot
    # (the same contract serve-mode per-run metrics.json has had since
    # PR 10: one-shot processes are exact, interactive sessions
    # accumulate — see Scheduler.persist).
    _live_plane = obs_mod.live.metrics()
    live_counts0 = _live_plane.counts() if _live_plane is not None else 0
    _attrib_led = obs_mod.attrib.ledger()
    attrib_counts0 = (
        _attrib_led.activity() if _attrib_led is not None else 0
    )
    _roofline_led = obs_mod.roofline.ledger()
    roofline_counts0 = (
        _roofline_led.activity() if _roofline_led is not None else 0
    )

    # Resume state (--resume): the crashed run's dir, conversation
    # history, and the panel answers its journal already completed — the
    # models those answers cover are NOT rerun.
    resume_dir = ""
    completed_responses: list = []
    if cfg.resume:
        resume_dir = os.path.join(cfg.data_dir, cfg.resume)
        manifest = (
            resume_manifest if resume_manifest is not None
            else load_resume_manifest(cfg.data_dir, cfg.resume)
        )
        history = [
            h for h in manifest.get("history", [])
            if isinstance(h, dict) and "prompt" in h and "consensus" in h
        ]
        completed_responses = load_panel_journal(resume_dir)

    # Conversation context: injected by interactive mode, or loaded from
    # --continue's saved run. Folded into the prompt the models (and
    # judge) see; Result.prompt / prompt.txt keep the raw follow-up.
    # Loaded first so a bad run-id fails fast — before provider init,
    # device placement, or the live progress display spin up.
    if history is None:
        history = []
        if cfg.continue_run:
            history = load_history(cfg.data_dir, cfg.continue_run)
    context_prompt = (
        render_conversation(history, cfg.prompt) if history else cfg.prompt
    )

    # Voting mode never queries a judge, so no judge provider (or judge
    # API key / judge chip slice) is required.
    judge = None if cfg.vote else cfg.judge
    registry = init_registry(cfg.models, judge, factory)

    # Announce the run composition so providers can plan device placement
    # (the tpu provider carves panel + judge onto disjoint mesh slices).
    seen: set = set()
    for model in dict.fromkeys(cfg.models + ([judge] if judge else [])):
        provider = registry.get(model)
        if id(provider) in seen:
            continue
        seen.add(id(provider))
        try:
            provider.prepare(cfg.models, judge)
        except Exception as err:
            raise CLIError(f"planning device placement: {err}") from err

    # Multi-controller execution: with several controller processes, each
    # queries only the models whose slice it can address; results merge
    # via one allgather and the judge's owner broadcasts the synthesis
    # (runner/multihost.py, parallel/multicontroller.py). Checked only
    # when on-device models are in play, so HTTP-only runs never touch
    # the JAX backend.
    multictrl = False
    mc = None
    if any(m.startswith("tpu:") for m in cfg.models + ([judge] if judge else [])):
        from llm_consensus_tpu.parallel import multicontroller as mc

        multictrl = mc.is_multicontroller()
    if multictrl:
        # Every controller must run the IDENTICAL prompt: argv/--file
        # reach all processes, but a stdin-piped prompt exists only on
        # the launching terminal — process 0's wins everywhere.
        context_prompt = mc.broadcast_json(context_prompt, owner=0)
        if cfg.resume:
            # The panel journal is process-0-local; a resumed run's
            # "skip these models" set would diverge across controllers
            # and deadlock the merge collective.
            raise CLIError(
                "--resume is not supported under multi-controller "
                "execution; rerun the prompt instead"
            )

    # Crash-safe run persistence: reserve the run dir and journal the
    # run's identity (run.json) BEFORE the panel fan-out, so a process
    # crash mid-run leaves a resumable dir instead of nothing. Panel
    # answers journal into <run_dir>/panel/ as they complete (atomic
    # per-model files via save_file); --resume reuses them. Runs that
    # disable auto-save (--output/--json/--no-save) keep the old
    # nothing-until-success behavior.
    run_dir = ""
    warn = (lambda msg: ui.print_error(stderr, msg)) if show_ui else None
    if resume_dir:
        run_dir = resume_dir
    elif (
        not cfg.output and not cfg.json and not cfg.no_save
        and not (multictrl and mc.process_index() != 0)
    ):
        try:
            _run_id, run_dir = reserve_run_dir(cfg.data_dir)
        except OSError as err:
            raise CLIError(f"creating run directory: {err}") from err
        write_run_manifest(run_dir, cfg, history, warn=warn)

    if show_ui:
        ui.print_header(stderr, cfg.prompt)
        ui.print_phase(stderr, "Querying models...")
        stderr.write("\n")

    # A resumed run queries only the models whose answers are NOT in the
    # panel journal (duplicates consume one journaled answer each).
    models_to_run = list(cfg.models)
    for resp in completed_responses:
        if resp.model in models_to_run:
            models_to_run.remove(resp.model)
    if cfg.resume and show_ui:
        ui.print_phase(
            stderr,
            f"Resuming {cfg.resume}: reusing {len(completed_responses)} "
            f"journaled answers, rerunning {len(models_to_run)} models",
        )

    progress = ui.Progress(stderr, models_to_run, quiet=not show_ui)
    progress.start()

    panel_priority = None
    judge_priority = None  # None → the Judge default (HIGH)
    if cfg.priority:
        from llm_consensus_tpu.pressure import parse_priority

        panel_priority = parse_priority(cfg.priority)
        # The documented contract: the judge outranks ITS OWN panel by
        # one class — an explicit low-priority batch run must not run
        # its judge at HIGH against other tenants.
        judge_priority = max(0, panel_priority - 1)
    if multictrl:
        from llm_consensus_tpu.runner.multihost import MultiControllerRunner

        runner = MultiControllerRunner(
            registry, cfg.timeout, max_tokens=cfg.max_tokens,
            system=cfg.system or None,
            owner_fn=lambda m: mc.model_owner(registry, m),
        )
    else:
        runner = Runner(
            registry, cfg.timeout, max_tokens=cfg.max_tokens,
            system=cfg.system or None, priority=panel_priority,
        )
    # Judge prefill overlap (consensus/overlap.py): panel answers prefill
    # into the judge engine's growing KV as they arrive, so synthesis
    # TTFT drops by nearly the whole judge-prompt prefill. Engages only
    # under --judge-overlap / LLMC_JUDGE_OVERLAP with a tpu judge;
    # multi-controller runs keep the classic broadcast path (the overlap
    # session is process-local, the broadcast is a collective).
    overlap_judge = None
    if not cfg.vote and not multictrl:
        from llm_consensus_tpu.consensus import make_overlap_judge

        try:
            overlap_judge = make_overlap_judge(
                registry.get(cfg.judge), cfg.judge, context_prompt,
                max_tokens=cfg.max_tokens,
                enabled=True if cfg.judge_overlap else None,
                priority=judge_priority,
            )
        except Exception:  # noqa: BLE001 — unknown judge errors later
            overlap_judge = None
    # Panel journal hook: each completed answer lands atomically in
    # <run_dir>/panel/ the moment its worker records it — the on-disk
    # half of crash-safe runs (--resume reads these back). Numbering
    # continues past reused answers so a resumed rerun never overwrites
    # the journal it is reusing.
    journal_response = None
    if run_dir:
        from llm_consensus_tpu.output.persist import save_file as _save_file

        panel_dir = os.path.join(run_dir, "panel")
        _panel_lock = sanitizer.make_lock("cli.panel")
        # Continue numbering past the highest EXISTING file, not the
        # count of parseable answers: a torn journal file still occupies
        # its index, and a rerun must never clobber a valid file it is
        # simultaneously reusing.
        _next = len(completed_responses)
        if os.path.isdir(panel_dir):
            for _name in os.listdir(panel_dir):
                _head = _name.split("-", 1)[0]
                if _head.isdigit():
                    _next = max(_next, int(_head) + 1)
        _panel_n = [_next]

        def journal_response(resp):
            with _panel_lock:
                n = _panel_n[0]
                _panel_n[0] += 1
            _save_file(
                panel_dir, f"{n:03d}-{_slug(resp.model)}.json",
                json.dumps(resp.to_dict(), indent=2), warn=warn,
            )

    response_hooks = [
        h for h in (
            journal_response,
            overlap_judge.on_response if overlap_judge is not None else None,
        ) if h is not None
    ]
    on_model_response = None
    if response_hooks:
        def on_model_response(resp):
            for hook in response_hooks:
                try:
                    hook(resp)
                except Exception:  # noqa: BLE001 — a hook must not fail a model
                    pass

    runner.with_callbacks(
        Callbacks(
            on_model_start=progress.model_started,
            on_model_stream=progress.model_streaming,
            on_model_complete=progress.model_completed,
            on_model_error=progress.model_failed,
            on_model_response=on_model_response,
        )
    )
    panel_prompt = context_prompt
    if cfg.vote:
        panel_prompt = render_vote_prompt(context_prompt, cfg.options)

    from llm_consensus_tpu.runner import AllModelsFailed, RunResult

    try:
        if models_to_run:
            result = runner.run(ctx, models_to_run, panel_prompt)
        else:
            # Every panel answer came from the journal: nothing to rerun.
            result = RunResult()
    except AllModelsFailed as err:
        if not completed_responses:
            progress.stop()
            raise CLIError(f"running queries: {err}") from err
        # The rerun wiped out, but journaled answers carry the run:
        # best-effort semantics, same as a partial panel failure.
        result = RunResult(
            warnings=[f"resumed rerun failed: {err}"],
            failed_models=list(dict.fromkeys(models_to_run)),
        )
    except Exception as err:
        progress.stop()
        raise CLIError(f"running queries: {err}") from err
    progress.stop()
    if completed_responses:
        result.responses[:0] = completed_responses

    agreement = score_agreement(result.responses)
    if show_ui:
        ui.print_success(stderr, f"Received responses from {len(result.responses)} models")
        if agreement is not None:
            ui.print_phase(
                stderr,
                f"Panel agreement: {agreement.level} ({agreement.score:.2f})",
            )
        stderr.write("\n")

    confidence = None
    if cfg.vote:
        # Voting mode (reference roadmap §2.3): host-side tally, no judge.
        vote_result = tally_votes(result.responses, cfg.options)
        consensus = vote_result.summary()
        judge_name = "vote"
        for m in vote_result.unparsed:
            result.warnings.append(f"{m}: no recognizable vote in response")
        if show_ui:
            ui.print_success(stderr, "Votes tallied!")
    else:
        if show_ui:
            ui.print_phase(stderr, "Synthesizing consensus...")
            stderr.write("\n")

        try:
            judge_provider = registry.get(cfg.judge)
        except Exception as err:
            raise CLIError(f"judge model {cfg.judge}: {err}") from err

        if multictrl:
            # The judge's owner runs the real synthesis on its slice; the
            # text (or the error, in lockstep) broadcasts to the rest.
            judge_provider = mc.BroadcastProvider(
                judge_provider, mc.model_owner(registry, cfg.judge)
            )

        judge = Judge(judge_provider, cfg.judge, max_tokens=cfg.max_tokens,
                      priority=judge_priority)
        judge_name = cfg.judge

        def synthesize(user_prompt: str, responses, syn=None) -> str:
            # ``syn``: round 1 may ride the overlap judge (its session
            # was fed during the panel fan-out); refinement rounds use
            # the classic judge — their prompt differs from the one the
            # overlap header was built with.
            syn = syn if syn is not None else judge
            judge_progress = ui.Progress(stderr, [cfg.judge], quiet=not show_ui)
            judge_progress.start()
            judge_progress.model_started(cfg.judge)
            try:
                text = syn.synthesize_stream(
                    ctx,
                    user_prompt,
                    responses,
                    lambda chunk: judge_progress.model_streaming(cfg.judge, chunk),
                )
            except Exception as err:
                judge_progress.stop()
                raise CLIError(f"consensus synthesis: {err}") from err
            judge_progress.model_completed(cfg.judge)
            judge_progress.stop()
            if syn.last_truncated:
                result.warnings.append(
                    f"{cfg.judge}: judge prompt truncated to fit context window"
                )
            return text

        consensus = synthesize(
            context_prompt, result.responses, syn=overlap_judge
        )

        # Multi-round refinement (reference roadmap §2.2): the panel
        # critiques the draft, the judge refines. Critique responses are
        # intermediate — the Result keeps round 1's panel answers. Later
        # rounds are best-effort like everything else: a failed round
        # becomes a warning and the run keeps the last good consensus
        # (tokens already paid must not be discarded).
        for round_no in range(2, cfg.rounds + 1):
            if show_ui:
                stderr.write("\n")
                ui.print_phase(stderr, f"Round {round_no}: panel critique...")
                stderr.write("\n")
            round_progress = ui.Progress(stderr, cfg.models, quiet=not show_ui)
            round_progress.start()
            runner.with_callbacks(Callbacks(
                on_model_start=round_progress.model_started,
                on_model_stream=round_progress.model_streaming,
                on_model_complete=round_progress.model_completed,
                on_model_error=round_progress.model_failed,
            ))
            try:
                critique = runner.run(
                    ctx, cfg.models, render_critique_prompt(context_prompt, consensus)
                )
            except Exception as err:
                round_progress.stop()
                result.warnings.append(
                    f"round {round_no} critique failed, keeping round "
                    f"{round_no - 1} consensus: {err}"
                )
                break
            round_progress.stop()
            result.warnings.extend(
                f"round {round_no}: {w}" for w in critique.warnings
            )
            if show_ui:
                stderr.write("\n")
                ui.print_phase(stderr, f"Round {round_no}: refining consensus...")
                stderr.write("\n")
            try:
                consensus = synthesize(
                    render_refine_prompt(context_prompt, consensus), critique.responses
                )
            except CLIError as err:
                result.warnings.append(
                    f"round {round_no} synthesis failed, keeping round "
                    f"{round_no - 1} consensus: {err}"
                )
                break

        if show_ui:
            ui.print_success(stderr, "Consensus reached!")

        if cfg.confidence:
            # Judge-graded confidence (roadmap §2.4): one extra judge
            # query; best-effort — a failed or unparseable grading is a
            # warning, never a failed run.
            if show_ui:
                stderr.write("\n")
                ui.print_phase(stderr, "Grading confidence...")
            try:
                graded = grade_confidence(
                    ctx, judge_provider, cfg.judge, context_prompt,
                    result.responses, consensus, max_tokens=cfg.max_tokens,
                )
            except Exception as err:  # noqa: BLE001
                result.warnings.append(f"confidence grading failed: {err}")
            else:
                if graded.score is None:
                    result.warnings.append(
                        "confidence grading returned an unparseable reply"
                    )
                else:
                    confidence = graded.to_dict()
                    if show_ui:
                        ui.print_success(
                            stderr,
                            f"Judge confidence: {graded.score}/100",
                        )
                        for point in graded.controversy:
                            stderr.write(f"  • {point}\n")

    out = output_mod.Result(
        prompt=cfg.prompt,
        responses=result.responses,
        consensus=consensus,
        judge=judge_name,
        warnings=result.warnings,
        failed_models=result.failed_models,
        history=history,
        agreement=agreement.to_dict() if agreement else None,
        confidence=confidence,
    )

    # Run telemetry (obs/): collected BEFORE the secondary-controller
    # early return — the multihost timeline merge is a collective, so
    # every process must enter it; only process 0 persists the artifacts.
    # Persistence rides the auto-saved run dir, so runs that disable it
    # (--output / --json / --no-save) skip the merge SYMMETRICALLY (cfg
    # is identical on every controller — no process enters a collective
    # the others skip) and say so instead of discarding telemetry
    # silently.
    from llm_consensus_tpu import faults as faults_mod

    telemetry_persists = (
        not cfg.output and not cfg.json and not cfg.no_save
    )
    trace_doc = metrics_doc = None
    if recorder is not None and not telemetry_persists:
        result.warnings.append(
            "run telemetry recorded but not persisted: trace.json/"
            "metrics.json ride the auto-saved run directory, which "
            "--output, --json, and --no-save disable"
        )
    if recorder is not None and telemetry_persists:
        from llm_consensus_tpu.obs import export as obs_export

        # Snapshot BEFORE the timeline merge: metrics.json must report
        # the degradation the RUN saw. A timeout in the telemetry
        # exchange itself still lands in the module's degraded set (its
        # liveness semantics are uniform) but surfaces here only as
        # timeline_missing_controllers, never as phantom run degradation
        # next to a result.json where every model succeeded.
        degraded_run = mc.degraded_peers() if multictrl else None
        if multictrl and cfg.events:
            # Merge only under the --events FLAG: argv reaches every
            # controller identically (the same contract every other flag
            # rides), so all processes enter the collective together —
            # whereas an env-enabled recorder (LLMC_EVENTS on one host
            # only) must stay local, or the lone merging process would
            # block its full deadline and mark healthy peers degraded.
            from llm_consensus_tpu.obs.multihost import merge_timelines

            trace_doc, trace_missing = merge_timelines(
                recorder, mc.allgather_timeout(ctx)
            )
        else:
            trace_doc, trace_missing = obs_export.local_trace(recorder), []
        batcher_stats = obs_export.collect_batcher_stats(registry)
        plan = faults_mod.plan()
        metrics_doc = obs_export.metrics_summary(
            recorder,
            responses=result.responses,
            batcher_stats=batcher_stats,
            kv_stats=obs_export.collect_kv_stats(registry),
            spec_stats=obs_export.collect_spec_stats(registry),
            disagg_stats=obs_export.collect_disagg_stats(registry),
            fault_trace=list(plan.trace) if plan is not None else None,
            degraded_peers=degraded_run,
            failed_models=result.failed_models,
            warnings=result.warnings,
            live=obs_export.live_summary(),
            attrib=obs_export.attrib_summary(),
            roofline=obs_export.roofline_summary(),
        )
        if trace_missing:
            metrics_doc["timeline_missing_controllers"] = sorted(
                trace_missing
            )
    elif telemetry_persists:
        # CLI parity with the serve-mode /metricsz scrape: even without
        # --events, a one-shot run whose live plane OR attribution
        # ledger observed anything (tpu engines record per-token latency
        # and device time by default; LLMC_ATTRIB=1 keeps the ledger on
        # with live histograms off) persists the final per-family
        # histogram quantiles and the chip-time attribution snapshot
        # into metrics.json, so the numbers a scrape would have shown
        # don't evaporate at process exit.
        from llm_consensus_tpu.obs import export as obs_export

        _lp = obs_mod.live.metrics()
        live_doc = (
            obs_export.live_summary(_lp)
            if _lp is not None and _lp.counts() > live_counts0 else None
        )
        _led = obs_mod.attrib.ledger()
        attrib_grew = (
            _led is not None and _led.activity() > attrib_counts0
        )
        _rl = obs_mod.roofline.ledger()
        roofline_grew = (
            _rl is not None and _rl.activity() > roofline_counts0
        )
        if live_doc or attrib_grew or roofline_grew:
            metrics_doc = obs_export.metrics_summary(
                responses=result.responses,
                failed_models=result.failed_models,
                warnings=result.warnings,
                live=live_doc,
                attrib=obs_export.attrib_summary(),
                roofline=obs_export.roofline_summary(),
            )

    if multictrl and mc.process_index() != 0:
        # Secondary controllers hold the identical merged result but own
        # no output: process 0 persists and prints exactly once.
        return out

    # Output routing (main.go:187-273): --output file, else the run dir
    # reserved BEFORE the fan-out (which routes result.json through the
    # same file-write branch), else --json stdout, else pretty TTY, else
    # JSON stdout.
    output_path = ""
    if cfg.output:
        output_path = cfg.output
    elif run_dir:
        try:
            output_path = save_aux_files(
                run_dir,
                cfg.prompt,
                consensus,
                warn=(lambda msg: ui.print_error(stderr, msg)) if show_ui else None,
            )
        except OSError as err:
            raise CLIError(f"creating run directory: {err}") from err

    if run_dir:
        # Telemetry artifacts live next to result.json in the run dir
        # (non-fatal writes, like the other aux files): trace.json +
        # metrics.json when events are on, and the exact injected fault
        # sequence whenever a fault plan drove this run.
        from llm_consensus_tpu.output.persist import save_file

        warn = (lambda msg: ui.print_error(stderr, msg)) if show_ui else None
        plan = faults_mod.plan()
        if plan is not None:
            save_file(run_dir, "faults.txt", plan.trace_bytes(), warn=warn)
        if trace_doc is not None:
            from llm_consensus_tpu.obs.export import save_run_telemetry

            save_run_telemetry(run_dir, trace_doc, metrics_doc, warn=warn)
        elif metrics_doc is not None:
            # Live-plane-only telemetry (no --events recorder): just
            # metrics.json — there is no event timeline to trace.
            import json as _json

            from llm_consensus_tpu.obs.export import METRICS_FILE

            save_file(
                run_dir, METRICS_FILE,
                _json.dumps(metrics_doc, indent=2) + "\n", warn=warn,
            )

    if output_path:
        # Atomic like every other run artifact: result.json's mere
        # EXISTENCE is the completion sentinel --resume keys on, so a
        # torn write would brick both --resume and --continue for the
        # run.
        from llm_consensus_tpu.output.persist import save_file as _sf

        _errs: list[str] = []
        written = _sf(
            os.path.dirname(output_path) or ".",
            os.path.basename(output_path),
            out.to_json(),
            warn=_errs.append,
        )
        if written is None:
            raise CLIError(
                "creating output file: "
                + (_errs[0] if _errs else output_path)
            )
        if show_ui:
            stderr.write("\n")
            ui.print_success(stderr, f"Run saved to {os.path.dirname(output_path) or '.'}")
    elif cfg.json:
        stdout.write(out.to_json())
    elif show_ui:
        stderr.write("\n")
        for resp in result.responses:
            ui.print_model_response(stderr, resp.model, resp.provider, resp.content, resp.latency_ms)
        ui.print_consensus(stderr, consensus)
        ui.print_summary(
            stderr,
            len(cfg.models),
            len(result.responses),
            len(result.failed_models),
            time.monotonic() - start_time,
        )
        ui.print_throughput(stderr, result.responses)
        if recorder is not None:
            from llm_consensus_tpu.obs.export import aggregate_throughput

            ui.print_aggregate(stderr, aggregate_throughput(recorder))
        if result.warnings:
            stderr.write("\n")
            for w in result.warnings:
                ui.print_error(stderr, w)
    else:
        stdout.write(out.to_json())
    return out


def interactive_loop(
    cfg: Config,
    ctx: Context,
    *,
    factory: ProviderFactory,
    stdin: TextIO,
    stdout: TextIO,
    stderr: TextIO,
) -> None:
    """REPL over warm providers (reference roadmap §7.2).

    Each line is a consensus query; the conversation accumulates across
    queries (same folding as --continue), and engines/compiled programs
    stay warm between them — the prefix cache makes follow-ups pay only
    for new tokens. Slash commands:

      /models            show the panel
      /models +m / -m    add / remove a model
      /judge m           change the judge
      /reset             clear the conversation history
      /exit, /quit       leave
    """
    tty = ui.is_terminal(stderr)
    history: list[dict] = []
    if cfg.continue_run:
        history = load_history(cfg.data_dir, cfg.continue_run)
    if tty:
        stderr.write(
            "Interactive mode: type a prompt, /models [+m|-m], /judge m, "
            "/reset, /exit\n"
        )

    # While idle at the prompt, a plain ctx.cancel() can't unblock
    # readline (Python retries it after EINTR, PEP 475) — so for the
    # REPL's lifetime SIGINT also raises KeyboardInterrupt, which aborts
    # the blocking read and exits the session promptly.
    prev_handler = None
    try:
        def _sigint(*_):
            ctx.cancel()
            raise KeyboardInterrupt

        prev_handler = signal.signal(signal.SIGINT, _sigint)
    except ValueError:
        prev_handler = None  # not the main thread (tests)

    try:
        while True:
            if ctx.done():
                return
            if tty:
                stderr.write("> ")
                stderr.flush()
            line = stdin.readline()
            if not line or ctx.done():
                return  # EOF or cancelled while blocked
            line = line.strip()
            if not line:
                continue
            cmd = line.split()[0]
            if cmd in ("/exit", "/quit"):
                return
            if cmd == "/reset":
                history = []
                if tty:
                    stderr.write("conversation cleared\n")
                continue
            if cmd == "/judge":
                parts = line.split()
                if len(parts) == 2:
                    cfg.judge = parts[1]
                stderr.write(f"judge: {cfg.judge}\n")
                continue
            if cmd == "/models":
                for tok in line.split()[1:]:
                    if tok.startswith("+"):
                        if tok[1:] and tok[1:] not in cfg.models:
                            cfg.models.append(tok[1:])
                    elif tok.startswith("-"):
                        if tok[1:] in cfg.models:
                            if len(cfg.models) == 1:
                                stderr.write(
                                    "cannot remove the last panel model\n"
                                )
                            else:
                                cfg.models.remove(tok[1:])
                stderr.write(f"models: {','.join(cfg.models)}\n")
                continue
            if cmd.startswith("/"):
                stderr.write(f"unknown command {cmd!r}\n")
                continue

            query_cfg = dataclasses_replace(cfg, prompt=line, continue_run="")
            try:
                out = _run(
                    query_cfg, ctx,
                    factory=factory, stdout=stdout, stderr=stderr,
                    history=list(history),
                )
            except CLIError as err:
                # One failed query must not end the session.
                stderr.write(f"error: {err}\n")
                continue
            history.append({"prompt": line, "consensus": out.consensus})
    except KeyboardInterrupt:
        return
    finally:
        if prev_handler is not None:
            try:
                signal.signal(signal.SIGINT, prev_handler)
            except ValueError:
                pass


def main(
    argv: Optional[list[str]] = None,
    *,
    factory: ProviderFactory = create_provider,
    stdin: Optional[TextIO] = None,
    stdout: Optional[TextIO] = None,
    stderr: Optional[TextIO] = None,
    install_signal_handlers: bool = True,
) -> int:
    argv = sys.argv[1:] if argv is None else argv
    stdin = sys.stdin if stdin is None else stdin
    stdout = sys.stdout if stdout is None else stdout
    stderr = sys.stderr if stderr is None else stderr

    if argv and argv[0] in ("serve", "route", "distill"):
        # Resident services: the serving gateway (cli/serve.py) and the
        # fleet router (cli/route.py) — own flag sets, own signal
        # handling (SIGTERM = graceful drain, not context cancel).
        # ``distill`` (cli/distill.py) is the flywheel's offline half:
        # journal → corpus → distilled checkpoint, one JSON summary.
        if argv[0] == "serve":
            from llm_consensus_tpu.cli.serve import serve_main as sub_main
        elif argv[0] == "distill":
            from llm_consensus_tpu.cli.distill import (
                distill_main as sub_main,
            )
        else:
            from llm_consensus_tpu.cli.route import route_main as sub_main

        try:
            return sub_main(
                argv[1:], stdout=stdout, stderr=stderr,
                install_signal_handlers=install_signal_handlers,
            )
        except CLIError as err:
            stderr.write(f"error: {err}\n")
            return 1
        except SystemExit as err:  # argparse --help / parse errors
            return int(err.code or 0)

    ctx = Context.background().with_cancel()
    if install_signal_handlers:
        # SIGINT/SIGTERM → graceful context cancel (main.go:90-91).
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                signal.signal(sig, lambda *_: ctx.cancel())
            except ValueError:
                break  # not the main thread (e.g. under a test runner)

    try:
        cfg = parse_args(argv, stdin, stdout)
        if cfg is None:
            return 0
        run(cfg, ctx, factory=factory, stdout=stdout, stderr=stderr, stdin=stdin)
    except CLIError as err:
        stderr.write(f"error: {err}\n")
        return 1
    except SystemExit as err:  # argparse --help / parse errors
        return int(err.code or 0)
    finally:
        ctx.close()
    return 0
