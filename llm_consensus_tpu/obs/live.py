"""The live metrics plane: continuous histograms + trace ids + SLO burn.

PR 2's :class:`~llm_consensus_tpu.obs.recorder.Recorder` answers "what
happened during THIS run" — a bounded event list exported post-hoc into
``trace.json``. A resident serving fleet (serve/, PRs 3/6/9) needs the
complementary question answered continuously: "what are the latency
tails RIGHT NOW, per priority class, per outcome" — without growing
memory, without a run lifecycle, and cheap enough to stay on forever.
That is :class:`LiveMetrics`:

  * **Fixed log-bucket histograms** (:class:`Histogram`) — every
    histogram in the fleet shares ONE bucket ladder (powers of two from
    100 µs), so histograms are *mergeable bucket-wise*: the router's
    fleet-wide ``/metricsz`` is literally the elementwise sum of its
    replicas' bucket arrays (obs/prom.py), associative and lossless.
    One observation costs a bisect into a 24-entry edge table plus three
    integer adds under the metrics lock.
  * **Windowed** (:class:`WindowedHistogram`) — each histogram keeps a
    cumulative total (what Prometheus scrapes: monotone counters) AND a
    ring of per-window snapshots (``LLMC_LIVE_WINDOW_S``, default 10 s),
    so recent-quantile questions ("p99 TTFT over the last window") are
    answered from bounded state — the SLO burn trigger reads these.
  * **Labels** — observations carry a priority class (``high`` /
    ``normal`` / ``low``) and an outcome (``ok`` / ``degraded`` /
    ``shed`` / ``preempted`` / ``failover`` / ``error``); each label
    combination owns its own histogram, created on first observation.

The standard metric names (the gateway/scheduler/provider observation
sites): ``ttft`` (request arrival → first streamed chunk), ``token_latency``
(per generated token), ``queue_wait`` (admission), ``e2e`` (request
arrival → done envelope), ``judge_synthesis`` (judge stream wall). All
values are seconds.

Resolution follows the faults/obs zero-cost pattern: :func:`metrics`
resolves ``LLMC_LIVE`` once (default ON — the live plane is the
always-available serving signal; ``LLMC_LIVE=0`` disables) and consumers
bind the result at construction time.

Trace ids (:func:`new_trace_id`) are minted here: the router (or the
gateway, for direct hits) assigns one per request; it propagates via the
``X-LLMC-Trace`` header through admission → scheduler → runner →
engine spans and returns to the client in the ``done`` envelope, so one
id recovers the full path of any slow request across failover and
spillover hops.
"""

from __future__ import annotations

import os
import threading
from bisect import bisect_left
from collections import deque
from typing import Callable, Optional
from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.utils import knobs

# One bucket ladder for the whole fleet: upper edges BUCKET_MIN * 2^i.
# 100 µs .. ~14 min covers sub-ms token cadence through multi-minute
# consensus runs; values past the top edge land in the +Inf bucket.
BUCKET_MIN = 1e-4
BUCKET_GROWTH = 2.0
N_BUCKETS = 23
BUCKET_EDGES: tuple = tuple(
    BUCKET_MIN * (BUCKET_GROWTH ** i) for i in range(N_BUCKETS)
)

DEFAULT_WINDOW_S = 10.0
DEFAULT_WINDOWS = 30  # ring depth: 5 minutes of 10 s windows

# Canonical label values (docs/architecture.md "Live observability").
OUTCOMES = ("ok", "degraded", "shed", "preempted", "failover", "error")
CLASS_NAMES = {0: "high", 1: "normal", 2: "low"}


def class_label(priority) -> str:
    """Priority class → label string (unknown/overflow classes keep
    their number, so a future class never crashes the metrics path)."""
    try:
        return CLASS_NAMES.get(int(priority), str(int(priority)))
    except (TypeError, ValueError):
        return "normal"


def bucket_index(value: float) -> int:
    """The bucket an observation lands in: the first edge >= value
    (Prometheus ``le`` semantics — upper bounds are inclusive);
    ``N_BUCKETS`` is the +Inf overflow bucket."""
    if value <= BUCKET_MIN:
        return 0
    return bisect_left(BUCKET_EDGES, value)


def new_trace_id() -> str:
    """A fresh 16-hex-char request trace id."""
    return os.urandom(8).hex()


class Histogram:
    """One fixed-log-bucket histogram: counts per bucket + count + sum.

    NOT internally locked — the owning :class:`LiveMetrics` (or a test)
    serializes access. Merge is elementwise, hence associative and
    commutative: ``merge(a, merge(b, c)) == merge(merge(a, b), c)``.
    """

    __slots__ = ("counts", "count", "sum")

    def __init__(self):
        self.counts = [0] * (N_BUCKETS + 1)  # [+Inf] is the last slot
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        self.counts[bucket_index(value)] += 1
        self.count += 1
        self.sum += value

    def merge_from(self, other: "Histogram") -> "Histogram":
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        return self

    def copy(self) -> "Histogram":
        out = Histogram()
        out.counts = list(self.counts)
        out.count = self.count
        out.sum = self.sum
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Estimated ``q``-quantile: linear interpolation inside the
        bucket the rank falls in (log buckets ⇒ the estimate is within
        one growth factor of exact; asserted in tests). None when empty.
        Overflow-bucket ranks report the top finite edge — an honest
        floor, not an invented tail."""
        if self.count <= 0:
            return None
        target = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target and c > 0:
                if i >= N_BUCKETS:
                    return BUCKET_EDGES[-1]
                lo = 0.0 if i == 0 else BUCKET_EDGES[i - 1]
                hi = BUCKET_EDGES[i]
                frac = (target - (cum - c)) / c
                return lo + (hi - lo) * max(0.0, min(1.0, frac))
        return BUCKET_EDGES[-1]

    def cumulative(self) -> list:
        """Cumulative bucket counts in edge order + the +Inf total —
        the Prometheus ``_bucket`` series (obs/prom.py renders these)."""
        out = []
        cum = 0
        for c in self.counts:
            cum += c
            out.append(cum)
        return out


class WindowedHistogram:
    """Cumulative total + a bounded ring of per-window histograms.

    ``total`` is what ``/metricsz`` exports (Prometheus histograms are
    monotone counters — scrapers compute rates themselves); the window
    ring answers "what happened recently" for the SLO burn watcher
    without unbounded state. NOT internally locked (see Histogram)."""

    __slots__ = ("total", "window", "ring")

    def __init__(self, windows: int = DEFAULT_WINDOWS):
        self.total = Histogram()
        self.window = Histogram()
        self.ring: deque = deque(maxlen=max(1, windows))

    def observe(self, value: float) -> None:
        self.total.observe(value)
        self.window.observe(value)

    def rotate(self) -> None:
        """Close the current window into the ring and start a new one."""
        self.ring.append(self.window)
        self.window = Histogram()

    def recent(self, n: int = 1) -> Histogram:
        """The merge of the last ``n`` CLOSED windows (the open window is
        excluded: a half-elapsed window under-counts and would flap any
        threshold read from it)."""
        out = Histogram()
        for h in list(self.ring)[-max(1, n):]:
            out.merge_from(h)
        return out


class LiveMetrics:
    """The process's live histogram families, keyed by (name, labels).

    Thread-safe: one lock serializes observation, rotation, and
    snapshot. A background rotator thread (started by the gateway via
    :meth:`start`; idempotent) closes windows every ``window_s`` and
    fires the registered rotation callbacks (the SLO watcher) — without
    it, histograms still accumulate; only recent-window reads stay
    empty.
    """

    def __init__(self, window_s: Optional[float] = None,
                 windows: Optional[int] = None):
        if window_s is None:
            window_s = knobs.get_float("LLMC_LIVE_WINDOW_S", DEFAULT_WINDOW_S)
        if windows is None:
            windows = knobs.get_int("LLMC_LIVE_WINDOWS", DEFAULT_WINDOWS)
        self.window_s = max(0.05, window_s)
        self._windows = max(1, windows)
        self._lock = sanitizer.make_lock("obs.live")
        self._hists: dict = {}  # (name, ((k, v), ...)) -> WindowedHistogram
        self._callbacks: list = []
        self._stop = sanitizer.make_event("obs.live.stop")
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _key(name: str, labels: dict) -> tuple:
        return (name, tuple(sorted(labels.items())))

    # -- writing -------------------------------------------------------------

    def observe(self, name: str, value: float, **labels) -> None:
        """Record one observation (seconds) into the labeled histogram,
        creating it on first use. Never raises — a metrics failure must
        not fail the request being measured."""
        try:
            value = float(value)
            if value < 0:
                value = 0.0
            key = self._key(name, labels)
            with self._lock:
                wh = self._hists.get(key)
                if wh is None:
                    wh = self._hists[key] = WindowedHistogram(self._windows)
                wh.observe(value)
        except Exception:  # noqa: BLE001
            pass

    def rotate(self) -> None:
        """Close every histogram's current window, then fire rotation
        callbacks (outside the lock — a callback may observe/dump)."""
        with self._lock:
            for wh in self._hists.values():
                wh.rotate()
            callbacks = list(self._callbacks)
        for fn in callbacks:
            try:
                fn(self)
            except Exception:  # noqa: BLE001
                pass

    def on_rotate(self, fn: Callable[["LiveMetrics"], None]) -> None:
        with self._lock:
            self._callbacks.append(fn)

    def remove_rotate(self, fn: Callable[["LiveMetrics"], None]) -> None:
        """Detach a rotation callback (a closed gateway must not stay
        reachable through the process-wide plane's callback list)."""
        with self._lock:
            try:
                self._callbacks.remove(fn)
            except ValueError:
                pass

    # -- reading -------------------------------------------------------------

    def families(self) -> dict:
        """{name: [(labels dict, cumulative-total Histogram copy)]} —
        a consistent snapshot for the Prometheus renderer."""
        with self._lock:
            items = [
                (name, dict(labels), wh.total.copy())
                for (name, labels), wh in self._hists.items()
            ]
        out: dict = {}
        for name, labels, hist in items:
            out.setdefault(name, []).append((labels, hist))
        return out

    def quantile_recent(self, name: str, q: float, windows: int = 1,
                        **label_filter) -> Optional[float]:
        """The ``q``-quantile of ``name`` over the last ``windows``
        closed windows, merged across every label set matching
        ``label_filter`` (empty filter = all). None when nothing was
        observed there."""
        with self._lock:
            whs = [
                wh for (n, labels), wh in self._hists.items()
                if n == name and all(
                    dict(labels).get(k) == v for k, v in label_filter.items()
                )
            ]
            merged = Histogram()
            for wh in whs:
                merged.merge_from(wh.recent(windows))
        return merged.quantile(q)

    def counts(self, name: Optional[str] = None) -> int:
        """Total observations recorded (optionally for one family)."""
        with self._lock:
            return sum(
                wh.total.count for (n, _), wh in self._hists.items()
                if name is None or n == name
            )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Start the window rotator thread (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._loop, name="llmc-live-rotate", daemon=True
            )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.window_s):
            try:
                self.rotate()
            except Exception:  # noqa: BLE001 — the rotator must not die
                continue

    def close(self) -> None:
        self._stop.set()


class SLOWatcher:
    """Anomaly trigger: p-quantile of a live metric over threshold for N
    consecutive closed windows ⇒ fire ``on_burn`` (the flight-recorder
    dump hook). Registered as a rotation callback, so it samples exactly
    once per window.

    Knobs: ``LLMC_SLO_TTFT_P99_S`` (threshold seconds; 0/unset disables)
    and ``LLMC_SLO_WINDOWS`` (consecutive windows, default 3).
    """

    def __init__(self, metric: str = "ttft", quantile: float = 0.99,
                 threshold_s: Optional[float] = None,
                 windows: Optional[int] = None,
                 on_burn: Optional[Callable[[dict], None]] = None):
        if threshold_s is None:
            threshold_s = knobs.get_float("LLMC_SLO_TTFT_P99_S")
        if windows is None:
            windows = knobs.get_int("LLMC_SLO_WINDOWS")
        self.metric = metric
        self.quantile = quantile
        self.threshold_s = threshold_s
        self.windows = max(1, windows)
        self.on_burn = on_burn
        self.burns = 0
        self._streak = 0

    @property
    def enabled(self) -> bool:
        return self.threshold_s > 0

    def check(self, live: LiveMetrics) -> bool:
        """One post-rotation sample; returns True when a burn fired.
        A quiet window (no observations) resets the streak — an idle
        server is not burning its SLO."""
        if not self.enabled:
            return False
        q = live.quantile_recent(self.metric, self.quantile, windows=1)
        if q is not None and q > self.threshold_s:
            self._streak += 1
        else:
            self._streak = 0
        if self._streak < self.windows:
            return False
        self._streak = 0  # re-arm: the NEXT burn needs N fresh windows
        self.burns += 1
        if self.on_burn is not None:
            try:
                self.on_burn({
                    "metric": self.metric,
                    "quantile": self.quantile,
                    "value_s": q,
                    "threshold_s": self.threshold_s,
                    "windows": self.windows,
                })
            except Exception:  # noqa: BLE001
                pass
        return True


# -- process-wide resolution (the faults/obs binding pattern) ----------------

_lock = sanitizer.make_lock("obs.live.registry")
_metrics: Optional[LiveMetrics] = None
_resolved = False


def metrics() -> Optional[LiveMetrics]:
    """The process-wide live metrics plane, or None when ``LLMC_LIVE=0``.

    Default ON: unlike the per-run Recorder, the live plane is bounded
    by construction (fixed buckets × bounded label sets × bounded window
    ring) and costs one dict hit + three adds per observation."""
    global _metrics, _resolved
    if not _resolved:
        with _lock:
            if not _resolved:
                if knobs.get_bool("LLMC_LIVE"):
                    _metrics = LiveMetrics()
                _resolved = True
    return _metrics


def install(m: Optional[LiveMetrics]) -> None:
    """Install ``m`` as the process live plane (tests / CLI flags)."""
    global _metrics, _resolved
    with _lock:
        old = _metrics
        _metrics = m
        _resolved = True
    if old is not None and old is not m:
        old.close()


def reset() -> None:
    """Forget the cached plane; the next :func:`metrics` re-reads env."""
    install(None)
    global _resolved
    with _lock:
        _resolved = False


__all__ = [
    "BUCKET_EDGES", "BUCKET_GROWTH", "BUCKET_MIN", "CLASS_NAMES",
    "DEFAULT_WINDOWS", "DEFAULT_WINDOW_S", "Histogram", "LiveMetrics",
    "N_BUCKETS", "OUTCOMES", "SLOWatcher", "WindowedHistogram",
    "bucket_index", "class_label", "install", "metrics", "new_trace_id",
    "reset",
]
