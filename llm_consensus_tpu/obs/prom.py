"""Prometheus text-format export for the live metrics plane.

``/metricsz`` serves text-format 0.0.4 — histograms as cumulative
``_bucket{le=...}`` series plus ``_sum``/``_count``, gauges for the
``/statsz`` snapshot blocks — because every serving fleet already has a
scraper that speaks it, and because the format is trivially *mergeable*:
the fleet router aggregates its replicas by fetching each replica's
``/metricsz``, parsing it back into bucket arrays (:func:`parse_text`),
summing bucket-wise (:func:`merge`), and re-rendering
(:func:`render_parsed`). Fixed shared bucket edges (obs/live.py) make
that sum exact — no re-bucketing, no quantile sketch drift. The
round-trip is canonical (sorted families, sorted labels, edge-ordered
buckets), so ``parse(render(x)) == x`` and the router-equals-merge
property is assertable in tests.

Naming scheme (docs/architecture.md "Live observability"):

  * histograms — ``llmc_<metric>_seconds`` with ``class`` (priority) and
    ``outcome`` labels: ``llmc_ttft_seconds``,
    ``llmc_token_latency_seconds``, ``llmc_queue_wait_seconds``,
    ``llmc_e2e_seconds``, ``llmc_judge_synthesis_seconds``;
  * gauges — the ``/statsz`` blocks flattened one numeric leaf per
    sample as ``llmc_stat{block="kv",key="<preset>.hit_tokens"}`` (block
    names and dotted key paths stay data, so arbitrary preset names
    never produce an illegal metric name), plus first-class
    ``llmc_load_score``, ``llmc_uptime_seconds``,
    ``llmc_obs_dropped_events``, and ``llmc_blackbox_dumps``.
"""

from __future__ import annotations

from typing import Iterable, Optional

from llm_consensus_tpu.obs.live import BUCKET_EDGES, Histogram, LiveMetrics

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
PREFIX = "llmc"

# The metric-family manifest: every family any surface may export, with
# its Prometheus type. PURE LITERAL on purpose — the static analyzer
# (analysis/metrics_docs.py, MD codes) parses it from the AST and
# cross-checks it three ways: families the code constructs must be
# declared here (MD01), declared families must have a row in
# docs/observability.md (MD02), and documented families must be
# declared (MD03). Add the family here AND a doc row when you add one;
# the runtime /metricsz lint (tests/test_attrib.py) keeps asserting
# what a live gateway actually exports.
FAMILIES = {
    "llmc_ttft_seconds": "histogram",
    "llmc_token_latency_seconds": "histogram",
    "llmc_queue_wait_seconds": "histogram",
    "llmc_e2e_seconds": "histogram",
    "llmc_judge_synthesis_seconds": "histogram",
    "llmc_route_e2e_seconds": "histogram",
    "llmc_device_time_seconds": "histogram",
    "llmc_host_gap_seconds": "histogram",
    "llmc_device_time_seconds_total": "counter",
    "llmc_tokens_total": "counter",
    "llmc_host_gap_seconds_total": "counter",
    "llmc_compiles_total": "counter",
    "llmc_retraces_total": "counter",
    "llmc_roofline_flops_total": "counter",
    "llmc_roofline_bytes_total": "counter",
    "llmc_roofline_dispatches_total": "counter",
    "llmc_roofline_tokens_total": "counter",
    "llmc_roofline_ridge_flops_per_byte": "gauge",
    "llmc_integrity_checks_total": "counter",
    "llmc_integrity_failures_total": "counter",
    "llmc_swap_vacate_seconds": "histogram",
    "llmc_weight_version": "gauge",
    "llmc_replica_up": "gauge",
    "llmc_replica_scrape_staleness_seconds": "gauge",
    "llmc_build_info": "gauge",
    "llmc_hbm_modeled_bytes": "gauge",
    "llmc_hbm_device_bytes": "gauge",
    "llmc_uptime_seconds": "gauge",
    "llmc_load_score": "gauge",
    "llmc_live_flights": "gauge",
    "llmc_runs_executed": "gauge",
    "llmc_obs_dropped_events": "gauge",
    "llmc_blackbox_dumps": "gauge",
    "llmc_stat": "gauge",
}

def _fmt(v: float) -> str:
    """Canonical sample/edge formatting: integers render bare (bucket
    counts), floats with repr (exact round-trip)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()):
        return str(int(v))
    return repr(float(v))


LE_STRS: tuple = tuple(_fmt(e) for e in BUCKET_EDGES) + ("+Inf",)


def _escape(v: str) -> str:
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _labels_str(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(merged.items())
    )
    return "{" + inner + "}"


def histogram_lines(metric: str, labels: dict, hist: Histogram) -> list:
    """One labeled histogram as its text-format sample lines."""
    name = f"{PREFIX}_{metric}_seconds"
    out = []
    cum = hist.cumulative()
    for le, c in zip(LE_STRS, cum):
        out.append(
            f"{name}_bucket{_labels_str(labels, {'le': le})} {c}"
        )
    out.append(f"{name}_sum{_labels_str(labels)} {_fmt(hist.sum)}")
    out.append(f"{name}_count{_labels_str(labels)} {hist.count}")
    return out


def flatten_numeric(doc, prefix: str = "") -> Iterable:
    """Yield ``(dotted.path, value)`` for every numeric leaf of a nested
    stats dict (bools excluded — they are states, not quantities; a
    scraper alarms on counters)."""
    if isinstance(doc, dict):
        for k in sorted(doc, key=str):
            path = f"{prefix}.{k}" if prefix else str(k)
            yield from flatten_numeric(doc[k], path)
    elif isinstance(doc, (int, float)) and not isinstance(doc, bool):
        yield (prefix, doc)


def render(
    live: Optional[LiveMetrics] = None,
    stats_blocks: Optional[dict] = None,
    gauges: Optional[dict] = None,
    families: Optional[dict] = None,
) -> str:
    """The full ``/metricsz`` body: live histogram families + ``/statsz``
    blocks flattened into ``llmc_stat`` gauges + first-class gauges +
    LABELED counter/gauge families (``families`` maps a bare family name
    to ``{"type": "counter"|"gauge", "samples": [(labels dict, value),
    ...]}`` — the chip-time attribution counters and ``build_info`` ride
    this)."""
    lines: list = []
    hist_families = live.families() if live is not None else {}
    for metric in sorted(hist_families):
        lines.append(f"# TYPE {PREFIX}_{metric}_seconds histogram")
        for labels, hist in sorted(
            hist_families[metric], key=lambda lh: sorted(lh[0].items())
        ):
            lines.extend(histogram_lines(metric, labels, hist))
    if families:
        for fname in sorted(families):
            fam = families[fname]
            samples = fam.get("samples", [])
            if not samples:
                continue
            ftype = fam.get("type", "gauge")
            lines.append(f"# TYPE {PREFIX}_{fname} {ftype}")
            for labels, value in sorted(
                samples, key=lambda s: sorted(s[0].items())
            ):
                if not isinstance(value, (int, float)) or isinstance(
                    value, bool
                ):
                    continue
                lines.append(
                    f"{PREFIX}_{fname}{_labels_str(labels)} {_fmt(value)}"
                )
    if gauges:
        for gname in sorted(gauges):
            value = gauges[gname]
            if not isinstance(value, (int, float)) or isinstance(value, bool):
                continue
            lines.append(f"# TYPE {PREFIX}_{gname} gauge")
            lines.append(f"{PREFIX}_{gname} {_fmt(value)}")
    if stats_blocks:
        lines.append(f"# TYPE {PREFIX}_stat gauge")
        for block in sorted(stats_blocks, key=str):
            for path, value in flatten_numeric(stats_blocks[block]):
                labels = {"block": str(block), "key": path}
                lines.append(f"{PREFIX}_stat{_labels_str(labels)} {_fmt(value)}")
    return "\n".join(lines) + "\n"


# -- parse / merge (the router's fleet aggregation path) ---------------------


def _parse_labels(raw: str) -> dict:
    """``k="v",k2="v2"`` → dict, inverting :func:`_escape` exactly: the
    three legal text-format escapes (``\\\\``, ``\\"``, ``\\n``) decode;
    any other backslash pair is kept VERBATIM (a foreign exporter's
    nonstandard escape round-trips rather than silently dropping its
    backslash). Raises ``ValueError`` on an unquoted value — parse_text
    skips the line (an ``assert`` would vanish under ``python -O``)."""
    out: dict = {}
    i, n = 0, len(raw)
    while i < n:
        eq = raw.index("=", i)
        key = raw[i:eq].strip().lstrip(",").strip()
        if eq + 1 >= n or raw[eq + 1] != '"':
            raise ValueError(f"unquoted label value in {raw!r}")
        j = eq + 2
        buf = []
        while j < n:
            ch = raw[j]
            if ch == "\\" and j + 1 < n:
                nxt = raw[j + 1]
                if nxt == "n":
                    buf.append("\n")
                elif nxt in ('"', "\\"):
                    buf.append(nxt)
                else:
                    buf.append(ch)
                    buf.append(nxt)
                j += 2
                continue
            if ch == '"':
                break
            buf.append(ch)
            j += 1
        else:
            raise ValueError(f"unterminated label value in {raw!r}")
        out[key] = "".join(buf)
        i = j + 1
    return out


def _split_sample(line: str) -> "tuple[str, dict, float]":
    """One sample line → ``(name, labels, value)``, quote-aware: the
    label block ends at the first ``}`` OUTSIDE a quoted value (a value
    containing ``}`` or ``" "`` must not truncate the block the way a
    bare ``rstrip``/``rsplit`` would), and an optional trailing
    timestamp — legal text format — is ignored instead of being read as
    the sample value."""
    brace = line.find("{")
    if brace >= 0:
        j, n = brace + 1, len(line)
        in_quotes = False
        while j < n:
            ch = line[j]
            if in_quotes:
                if ch == "\\":
                    j += 2
                    continue
                if ch == '"':
                    in_quotes = False
            elif ch == '"':
                in_quotes = True
            elif ch == "}":
                break
            j += 1
        if j >= n:
            raise ValueError(f"unterminated label block in {line!r}")
        name = line[:brace]
        labels = _parse_labels(line[brace + 1:j])
        tail = line[j + 1:]
    else:
        name, _, tail = line.partition(" ")
        labels = {}
    fields = tail.split()
    if not fields:
        raise ValueError(f"sample without value in {line!r}")
    return name, labels, float(fields[0])


def parse_text(text: str) -> dict:
    """Parse a ``/metricsz`` body into a mergeable structure:

    ``{"histograms": {(metric, labels-tuple): {"buckets": {le: n},
    "sum": s, "count": n}}, "gauges": {(name, labels-tuple): v},
    "types": {bare-family-name: declared type}}``.

    ``types`` records each family's ``# TYPE`` declaration so the
    router's re-render (:func:`render_parsed`) keeps counters counters —
    a strict scraper must not see a replica's ``llmc_tokens_total``
    counter come back from the fleet endpoint re-typed as a gauge.

    Only ``llmc_``-prefixed families are read; unknown lines are
    skipped, so a replica running a newer build never breaks the
    router's aggregation.
    """
    hists: dict = {}
    gauges: dict = {}
    types: dict = {}
    suffix = "_seconds"
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            if line.startswith("# TYPE "):
                parts = line[len("# TYPE "):].split()
                if len(parts) == 2 and parts[0].startswith(PREFIX + "_"):
                    types[parts[0][len(PREFIX) + 1:]] = parts[1]
            continue
        try:
            name, labels, value = _split_sample(line)
            if not name.startswith(PREFIX + "_"):
                continue
            base = name[len(PREFIX) + 1:]
            if base.endswith("_bucket") and base[:-7].endswith(suffix):
                metric = base[:-7][: -len(suffix)]
                le = labels.pop("le", "+Inf")
                key = (metric, tuple(sorted(labels.items())))
                h = hists.setdefault(
                    key, {"buckets": {}, "sum": 0.0, "count": 0}
                )
                h["buckets"][le] = h["buckets"].get(le, 0) + value
            elif base.endswith("_sum") and base[:-4].endswith(suffix):
                metric = base[:-4][: -len(suffix)]
                key = (metric, tuple(sorted(labels.items())))
                h = hists.setdefault(
                    key, {"buckets": {}, "sum": 0.0, "count": 0}
                )
                h["sum"] += value
            elif base.endswith("_count") and base[:-6].endswith(suffix):
                metric = base[:-6][: -len(suffix)]
                key = (metric, tuple(sorted(labels.items())))
                h = hists.setdefault(
                    key, {"buckets": {}, "sum": 0.0, "count": 0}
                )
                h["count"] += value
            else:
                gauges[(base, tuple(sorted(labels.items())))] = (
                    gauges.get((base, tuple(sorted(labels.items()))), 0.0)
                    + value
                )
        except (ValueError, AssertionError, IndexError):
            continue  # unknown/malformed line: skip, never fail the scrape
    return {"histograms": hists, "gauges": gauges, "types": types}


def merge(parsed_docs: list) -> dict:
    """Bucket-wise merge of parsed ``/metricsz`` documents: histogram
    bucket counts / sums / counts add per (metric, labels, le); gauges
    add per (name, labels) — the fleet view is the sum of its replicas
    (rates and occupancies are per-replica truths; operators read them
    per replica, the fleet totals are for counters)."""
    out = {"histograms": {}, "gauges": {}, "types": {}}
    for doc in parsed_docs:
        out["types"].update(doc.get("types", {}))
        for key, h in doc.get("histograms", {}).items():
            dst = out["histograms"].setdefault(
                key, {"buckets": {}, "sum": 0.0, "count": 0}
            )
            for le, n in h["buckets"].items():
                dst["buckets"][le] = dst["buckets"].get(le, 0) + n
            dst["sum"] += h["sum"]
            dst["count"] += h["count"]
        for key, v in doc.get("gauges", {}).items():
            out["gauges"][key] = out["gauges"].get(key, 0.0) + v
    return out


def _le_sort_key(le: str):
    return float("inf") if le == "+Inf" else float(le)


def render_parsed(doc: dict) -> str:
    """Render a parsed/merged document back to canonical text — the
    router's ``/metricsz`` body. Families render contiguously with ONE
    ``# TYPE`` line each (strict text-format parsers reject a family
    split around metadata)."""
    lines: list = []
    hists = doc.get("histograms", {})
    by_metric: dict = {}
    for (metric, labels), h in hists.items():
        by_metric.setdefault(metric, []).append((dict(labels), h))
    for metric in sorted(by_metric):
        name = f"{PREFIX}_{metric}_seconds"
        lines.append(f"# TYPE {name} histogram")
        for labels, h in sorted(
            by_metric[metric], key=lambda lh: sorted(lh[0].items())
        ):
            for le in sorted(h["buckets"], key=_le_sort_key):
                lines.append(
                    f"{name}_bucket{_labels_str(labels, {'le': le})} "
                    f"{_fmt(h['buckets'][le])}"
                )
            lines.append(f"{name}_sum{_labels_str(labels)} {_fmt(h['sum'])}")
            lines.append(
                f"{name}_count{_labels_str(labels)} {_fmt(h['count'])}"
            )
    gauges = doc.get("gauges", {})
    types = doc.get("types", {})
    prev_family = None
    for (gname, labels) in sorted(gauges, key=lambda k: (k[0], k[1])):
        if gname != prev_family:
            prev_family = gname
            # Keep the replica's declared type (counters stay counters
            # through the fleet merge); unknown families default gauge.
            lines.append(
                f"# TYPE {PREFIX}_{gname} {types.get(gname, 'gauge')}"
            )
        lines.append(
            f"{PREFIX}_{gname}{_labels_str(dict(labels))} "
            f"{_fmt(gauges[(gname, labels)])}"
        )
    return "\n".join(lines) + "\n"


__all__ = [
    "CONTENT_TYPE", "LE_STRS", "PREFIX", "flatten_numeric",
    "histogram_lines", "merge", "parse_text", "render", "render_parsed",
]
