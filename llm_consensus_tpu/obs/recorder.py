"""Run-wide telemetry: spans, counters, and instant events on one timeline.

A :class:`Recorder` collects three event kinds from every subsystem of a
run — engine dispatch/fetch, the batcher scheduler loop, runner workers,
the multi-controller exchange, SSE streams, fault injection — onto one
``time.monotonic_ns`` timeline:

  * **spans** — an interval with a duration (a prefill, a decode-chunk
    dispatch, an allgather wait). Recorded either after the fact via
    ``complete(name, t0)`` (the hot-path form: one clock read before the
    work, one event append after) or with the ``span(...)`` context
    manager on cool paths.
  * **instants** — a point on the timeline (an injected fault, an SSE
    chunk arrival, a degraded-mode transition).
  * **counters** — run-aggregate numbers (tokens decoded, decode seconds,
    chunks fetched) exported into ``metrics.json``; they carry no
    timestamp and cost one dict update.

Events carry a ``tid`` — a *subsystem* label ("engine", "batcher",
"runner", "mc", "sse", "faults"), not a Python thread id: the timeline's
useful rows are pipeline stages, and thread ids churn per run. The Chrome
trace exporter (obs/export.py) maps labels to stable integer tids with
``thread_name`` metadata, so Perfetto shows named rows.

The recorder follows the faults-package zero-cost pattern exactly
(faults/__init__.py): ``obs.recorder()`` resolves ``LLMC_EVENTS`` once per
process and consumers bind the result at construction time
(``self._obs = obs.recorder()``) — with events disabled the hot dispatch
and fetch loops carry a single bound ``is not None`` check and touch no
recorder state (asserted in tests/test_obs.py).

Memory is bounded: past ``max_events`` (``LLMC_EVENTS_MAX``, default
200k ≈ tens of MB of trace JSON) new events are counted as dropped, never
appended — a long serving run must not grow host memory without bound.
Drops are accounted, not silent: the ``obs.dropped_events`` counter
exports into metrics.json and ``/metricsz``, and the first drop appends
a one-time ``events_dropped`` warning instant (one event past the cap)
so a truncated timeline says so on its own face.
"""

from __future__ import annotations

import threading
import time

from llm_consensus_tpu.analysis import sanitizer
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Optional

DEFAULT_MAX_EVENTS = 200_000


@dataclass(frozen=True)
class Event:
    """One timeline event. ``ph`` is the Chrome trace phase this event
    exports as: "X" (complete span, ``dur_ns`` set) or "i" (instant)."""

    name: str
    ph: str
    ts_ns: int
    tid: str
    dur_ns: int = 0
    args: dict = field(default_factory=dict)


class Recorder:
    """Thread-safe span/counter/instant recorder for one run.

    All mutation happens under one lock; ``events()``/``counters()``
    return copies, so exporters and the live UI read consistent state
    while workers keep appending.
    """

    def __init__(self, max_events: int = DEFAULT_MAX_EVENTS):
        self._lock = sanitizer.make_lock("obs.recorder")
        self._events: list[Event] = []
        self._counters: dict[str, float] = {}
        self._max_events = max_events
        self.dropped = 0
        self._drop_warned = False

    # -- clock ---------------------------------------------------------------

    @staticmethod
    def now() -> int:
        """Timeline clock: monotonic nanoseconds. All events (and the
        multihost clock-offset estimate) use this one clock."""
        return time.monotonic_ns()

    # -- recording -----------------------------------------------------------

    def _append(self, ev: Event) -> None:
        with self._lock:
            if len(self._events) >= self._max_events:
                # Dropped, not silently: the counter exports as
                # ``obs.dropped_events`` (metrics.json, /metricsz), and
                # the FIRST drop appends a one-time warning instant —
                # one event past the cap, so the truncation itself is
                # visible on the timeline it truncated.
                self.dropped += 1
                self._counters["obs.dropped_events"] = (
                    self._counters.get("obs.dropped_events", 0.0) + 1.0
                )
                if not self._drop_warned:
                    self._drop_warned = True
                    self._events.append(Event(
                        name="events_dropped", ph="i",
                        ts_ns=time.monotonic_ns(), tid="obs",
                        args={"max_events": self._max_events},
                    ))
                return
            self._events.append(ev)

    def complete(self, name: str, t0_ns: int, tid: str = "main",
                 **args) -> None:
        """Record a span that started at ``t0_ns`` (from :meth:`now`) and
        ends now — the hot-path form: the caller pays one clock read up
        front and one append here, nothing else."""
        t1 = time.monotonic_ns()
        self._append(Event(
            name=name, ph="X", ts_ns=t0_ns, tid=tid,
            dur_ns=max(t1 - t0_ns, 0), args=args,
        ))

    @contextmanager
    def span(self, name: str, tid: str = "main", **args):
        """Span context manager for cool paths (the body's exceptions
        still record the span — a failed prefill's wall time is exactly
        what the timeline must show)."""
        t0 = time.monotonic_ns()
        try:
            yield
        finally:
            self.complete(name, t0, tid=tid, **args)

    def instant(self, name: str, tid: str = "main", **args) -> None:
        self._append(Event(
            name=name, ph="i", ts_ns=time.monotonic_ns(), tid=tid, args=args,
        ))

    def count(self, name: str, value: float = 1.0) -> None:
        """Accumulate a run-aggregate counter (no timestamp)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    # -- reading -------------------------------------------------------------

    def events(self) -> list[Event]:
        with self._lock:
            return list(self._events)

    def depth(self) -> int:
        """Recorded-event count WITHOUT copying the list (stats scrapes
        poll this; a 200k-event copy per scrape is pure waste)."""
        with self._lock:
            return len(self._events)

    def counters(self) -> dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def span_names(self) -> set[str]:
        """Distinct names of recorded spans (export goldens / CI gates)."""
        with self._lock:
            return {e.name for e in self._events if e.ph == "X"}

    def clear(self) -> None:
        """Drop recorded events and counters (the CLI's per-query reset:
        consumers keep their bound reference — interactive sessions reuse
        warm engines — so the recorder empties in place rather than being
        replaced)."""
        with self._lock:
            self._events.clear()
            self._counters.clear()
            self.dropped = 0
            self._drop_warned = False


def resolve_max_events() -> int:
    from llm_consensus_tpu.utils import knobs

    return knobs.get_int("LLMC_EVENTS_MAX", DEFAULT_MAX_EVENTS)


__all__ = ["DEFAULT_MAX_EVENTS", "Event", "Recorder", "resolve_max_events"]
