"""Telemetry entry point: the process-wide recorder.

``recorder()`` resolves ``LLMC_EVENTS`` exactly once and caches the result
(None when unset/0) — the same zero-cost pattern as faults/__init__.py.
Consumers bind the recorder at construction time
(``self._obs = obs.recorder()``) so disabled runs pay a single bound
``is not None`` check on the hot dispatch/fetch paths; the enable decision
is made at recorder-resolution time, never per-event.

``install()`` / ``reset()`` exist for tests, the CLI's ``--events`` flag,
and the events dryrun lane, which enable telemetry mid-process (before any
engine/batcher/runner is constructed); production resolves from the
environment.

Sibling planes with the same resolution pattern:

  * ``obs.live`` — the continuous serving metrics (windowed mergeable
    histograms behind ``/metricsz``) and request trace ids;
  * ``obs.blackbox`` — the always-on flight recorder ring that dumps a
    Perfetto snapshot on crash/pressure/SLO-burn anomalies;
  * ``obs.attrib`` — chip-time attribution: device time per program
    family, the goodput token ledger, host-gap (bubble) detection, and
    the retrace / HBM-watermark sentinels;
  * ``obs.roofline`` — per-program static costs (XLA cost analysis at
    lowering time) joined with the attrib walls into live achieved-
    FLOPs/s / bytes/s and compute-vs-memory-bound verdicts;
  * ``obs.profiler`` — the on-demand bounded ``jax.profiler`` window
    behind ``POST /debugz/profile``.
"""

from __future__ import annotations

import threading
from typing import Optional

from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.obs import (  # noqa: F401 — public API
    attrib, blackbox, live, profiler, roofline)
from llm_consensus_tpu.obs.recorder import (  # noqa: F401 — public API
    Event, Recorder, resolve_max_events)
from llm_consensus_tpu.utils import knobs

__all__ = [
    "Event", "Recorder", "attrib", "blackbox", "live", "profiler",
    "roofline", "recorder", "install", "reset",
]

_lock = sanitizer.make_lock("obs.registry")
_recorder: Optional[Recorder] = None
_resolved = False


def recorder() -> Optional[Recorder]:
    """The process-wide recorder, or None when telemetry is disabled."""
    global _recorder, _resolved
    if not _resolved:
        with _lock:
            if not _resolved:
                env = knobs.get_str("LLMC_EVENTS")
                if env and env != "0":
                    _recorder = Recorder(max_events=resolve_max_events())
                _resolved = True
    return _recorder


def install(r: Optional[Recorder]) -> None:
    """Install ``r`` as the process recorder (tests / --events / dryrun)."""
    global _recorder, _resolved
    with _lock:
        _recorder = r
        _resolved = True


def reset() -> None:
    """Forget the cached recorder; the next ``recorder()`` re-reads the
    environment."""
    global _recorder, _resolved
    with _lock:
        _recorder = None
        _resolved = False
