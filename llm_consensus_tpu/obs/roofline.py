"""Per-program roofline attribution: WHY is this family slow?

PR 11's chip-time ledger answers *where* device seconds go (family
walls); this module answers *why* each family runs at the rate it does.
At dispatch time every instrumented jitted program captures — once per
``(family, bucket-shape)`` key — the compiler's own static cost model
(``jax.stages.Lowered.cost_analysis()``: FLOPs, bytes accessed, output
bytes), and every subsequent dispatch just bumps counters. Joining the
accumulated static costs with the measured per-family walls the
attribution ledger already books yields live achieved-FLOPs/s,
achieved-bytes/s, arithmetic intensity (FLOPs/byte), and a
compute-vs-memory-bound verdict against the device's balance point
(ridge = peak FLOPs / peak HBM bytes/s where the chip is known,
``LLMC_ROOFLINE_RIDGE`` otherwise) — the machine-checked form of the
"judge decode MFU 0.0075 because decode is bandwidth-bound" diagnosis.

Capture deliberately uses the LOWERED (pre-optimization) cost analysis:

  * ``Lowered.cost_analysis()`` never triggers an XLA backend compile,
    so capture cannot fire the retrace sentinel or pay a second
    multi-second compile — measured: trace+lower only;
  * the unoptimized HLO counts operand bytes arithmetically (operands +
    outputs), which is the roofline convention; the post-fusion
    ``Compiled`` numbers change meaning across backends.

XLA counts a ``while``/``scan`` BODY once regardless of trip count, so
dispatch sites whose program loops on device (the decode chunk's
``lax.scan``, the chunked-prefill ``fori_loop``) pass the host-known
``steps`` multiplier per dispatch; everything else defaults to 1.

Cross-check: engines register their analytic per-token costs
(:func:`note_modeled`, utils/flops — the same model behind the
modeled-MFU gauges), and :meth:`RooflineLedger.snapshot` compares the
cost-analysis FLOPs-per-token against the modeled range per family
(``LLMC_ROOFLINE_TOL``) — the two ledgers cannot silently diverge.

Resolution follows the attrib pattern: ``LLMC_ROOFLINE=0`` disables,
``=1`` forces on, unset follows chip-time attribution (the walls this
module joins against). Hot-path cost when enabled: one dict lookup +
a few counter bumps per *dispatch* (not per token); when disabled, one
module-global None check.
"""

from __future__ import annotations

import threading
from functools import wraps
from typing import Callable, Optional

from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.utils import knobs

# Fallback balance point (FLOPs per byte) when the device peaks are
# unknown (CPU dev runs): low enough that a batched prefill (hundreds
# of tokens per weight read) lands compute-bound, high enough that a
# small-batch decode chunk (a few FLOPs per weight byte) lands
# memory-bound — the split every real accelerator in utils/flops.py
# also produces (their ridges sit at 140-560).
DEFAULT_RIDGE = 32.0
# Modeled-vs-cost-analysis tolerance: the ratio of XLA-counted to
# analytic FLOPs/token must sit in [1/tol, tol]. The analytic 2·N rule
# and XLA's dot accounting agree to well within 2x; 4.0 leaves room for
# elementwise/softmax traffic on tiny dev configs.
DEFAULT_TOL = 4.0

_SENTINEL_KEY = ()


class RooflineLedger:
    """Process-wide static-cost x measured-wall roofline accounting.

    Thread-safe: one lock serializes counter writes; the one-time cost
    capture per key runs OUTSIDE the lock (tracing + lowering a big
    model takes real time) behind an in-progress marker so concurrent
    first dispatches of one bucket capture once. Telemetry never
    raises: a failed capture is cached as a zero-cost record and the
    family still counts dispatches.
    """

    def __init__(self, ridge: Optional[float] = None,
                 tol: Optional[float] = None):
        if ridge is None:
            ridge = knobs.get_float("LLMC_ROOFLINE_RIDGE", 0.0)
        if tol is None:
            tol = knobs.get_float("LLMC_ROOFLINE_TOL", DEFAULT_TOL)
        # A positive ridge pins the balance point outright (knob or
        # constructor); 0 defers to device peaks with the documented
        # fallback off-accelerator.
        self.ridge_override = ridge if ridge and ridge > 0 else None
        self.fallback_ridge = DEFAULT_RIDGE
        self.tol = max(1.0, tol)
        self._lock = sanitizer.make_lock("obs.roofline")
        # (family, key) -> program record. "raw_*" are the per-dispatch
        # static costs at steps=1; totals accumulate raw x steps.
        self._programs: dict = {}
        self._capturing: set = set()
        # Dispatches that landed while their key's capture was in
        # flight: [dispatches, steps, tokens], merged when it finishes.
        self._deferred: dict = {}
        # Per-family extras the compiler cannot see: cross-mesh
        # device_put transfer bytes (the kv_handoff wall's traffic).
        self._transfer_bytes: dict = {}
        # family -> (min, max) analytic per-token costs registered by
        # engines (utils/flops) — the cross-check's modeled side.
        self._modeled_fpt: dict = {}
        self._modeled_bpt: dict = {}
        self._peaks_resolved = False
        self._peak_flops: Optional[float] = None
        self._peak_bw: Optional[float] = None
        self._n_devices = 1

    # -- capture + dispatch ---------------------------------------------------

    def dispatch(self, family: str, key: tuple, fn, args, kwargs,
                 tokens: int = 0, steps: int = 1) -> None:
        """Book one dispatch of ``fn`` under ``(family, key)``; capture
        its static cost on first sight. Never raises."""
        pkey = (family, key)
        with self._lock:
            rec = self._programs.get(pkey)
            if rec is not None:
                rec["dispatches"] += 1
                rec["steps"] += steps
                rec["tokens"] += tokens
                return
            if pkey in self._capturing:
                # A concurrent first dispatch is lowering this bucket
                # right now; book the counts aside — the capture merges
                # them when it lands.
                d = self._deferred.setdefault(pkey, [0, 0, 0])
                d[0] += 1
                d[1] += steps
                d[2] += tokens
                return
            self._capturing.add(pkey)
        raw = self._capture(fn, args, kwargs)
        with self._lock:
            self._capturing.discard(pkey)
            deferred = self._deferred.pop(pkey, (0, 0, 0))
            rec = self._programs.setdefault(pkey, {
                "dispatches": 0, "steps": 0, "tokens": 0, **raw,
            })
            rec["dispatches"] += 1 + deferred[0]
            rec["steps"] += steps + deferred[1]
            rec["tokens"] += tokens + deferred[2]

    @staticmethod
    def _capture(fn, args, kwargs) -> dict:
        """One program's static costs via the lowered (pre-optimization)
        cost analysis; zeros with source="none" when the backend offers
        nothing."""
        try:
            ca = fn.lower(*args, **kwargs).cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            flops = float(ca.get("flops") or 0.0)
            bytes_ = float(ca.get("bytes accessed") or 0.0)
            out_b = float(ca.get("bytes accessedout{}") or 0.0)
            if flops <= 0.0 and bytes_ <= 0.0:
                return {"raw_flops": 0.0, "raw_bytes": 0.0,
                        "raw_out_bytes": 0.0, "source": "none"}
            return {"raw_flops": flops, "raw_bytes": bytes_,
                    "raw_out_bytes": out_b, "source": "xla"}
        except Exception:  # noqa: BLE001 — telemetry never raises
            return {"raw_flops": 0.0, "raw_bytes": 0.0,
                    "raw_out_bytes": 0.0, "source": "none"}

    def note_transfer(self, family: str, nbytes: float) -> None:
        """Book raw transfer bytes the compiler cannot see (the
        cross-mesh handoff's device_put)."""
        if nbytes <= 0:
            return
        with self._lock:
            self._transfer_bytes[family] = (
                self._transfer_bytes.get(family, 0.0) + float(nbytes)
            )

    def note_modeled(self, family: str, flops_per_token: float,
                     bytes_per_token: Optional[float] = None) -> None:
        """Register an engine's analytic per-token costs for ``family``
        (the modeled-MFU model, utils/flops) — the cross-check baseline.
        Multiple engines widen the accepted range."""
        with self._lock:
            if flops_per_token and flops_per_token > 0:
                lo, hi = self._modeled_fpt.get(
                    family, (flops_per_token, flops_per_token)
                )
                self._modeled_fpt[family] = (
                    min(lo, flops_per_token), max(hi, flops_per_token)
                )
            if bytes_per_token and bytes_per_token > 0:
                lo, hi = self._modeled_bpt.get(
                    family, (bytes_per_token, bytes_per_token)
                )
                self._modeled_bpt[family] = (
                    min(lo, bytes_per_token), max(hi, bytes_per_token)
                )

    # -- device peaks ---------------------------------------------------------

    def _peaks(self) -> "tuple[Optional[float], Optional[float], int]":
        """(peak FLOPs/s, peak HBM bytes/s, device count) per chip from
        the published-spec tables, or Nones off-accelerator. Resolved
        once; jax import stays off the dispatch path."""
        if not self._peaks_resolved:
            peak_f = peak_b = None
            n_dev = 1
            try:
                import jax

                from llm_consensus_tpu.utils import flops as flops_mod

                devices = jax.devices()
                n_dev = max(1, len(devices))
                kind = devices[0].device_kind
                peak_f = flops_mod.device_peak_flops(kind)
                peak_b = flops_mod.device_peak_hbm_bw(kind)
            except Exception:  # noqa: BLE001
                pass
            with self._lock:
                self._peak_flops, self._peak_bw = peak_f, peak_b
                self._n_devices = n_dev
                self._peaks_resolved = True
        return self._peak_flops, self._peak_bw, self._n_devices

    def ridge(self) -> "tuple[float, str]":
        """(FLOPs-per-byte balance point, its provenance): the chip's
        peak ratio when both peaks are known, the fallback knob off-
        accelerator."""
        if self.ridge_override is not None:
            return self.ridge_override, "override"
        peak_f, peak_b, _ = self._peaks()
        if peak_f and peak_b:
            return peak_f / peak_b, "device"
        return self.fallback_ridge, "default"

    # -- reading --------------------------------------------------------------

    def activity(self) -> int:
        with self._lock:
            return sum(r["dispatches"] for r in self._programs.values())

    def snapshot(self, device_s: Optional[dict] = None) -> dict:
        """The /statsz ``roofline`` block: per-family static costs
        joined with measured walls, verdicts against the ridge, and the
        modeled-vs-cost-analysis cross-check. ``device_s`` is the attrib
        ledger's per-family wall dict; omitted, it is read from the
        installed ledger."""
        if device_s is None:
            device_s = self._attrib_walls()
        ridge, ridge_source = self.ridge()
        peak_f, peak_b, n_dev = self._peaks()
        with self._lock:
            programs = {
                k: dict(v) for k, v in self._programs.items()
            }
            transfer = dict(self._transfer_bytes)
            modeled_fpt = dict(self._modeled_fpt)
            modeled_bpt = dict(self._modeled_bpt)
        fams: dict = {}
        for (family, key), rec in sorted(
            programs.items(), key=lambda kv: (kv[0][0], str(kv[0][1]))
        ):
            f = fams.setdefault(family, {
                "programs": 0, "dispatches": 0, "tokens": 0,
                "flops": 0.0, "bytes": 0.0, "out_bytes": 0.0,
                "sources": set(),
            })
            f["programs"] += 1
            f["dispatches"] += rec["dispatches"]
            f["tokens"] += rec["tokens"]
            f["flops"] += rec["raw_flops"] * rec["steps"]
            f["bytes"] += rec["raw_bytes"] * rec["steps"]
            f["out_bytes"] += rec["raw_out_bytes"] * rec["steps"]
            f["sources"].add(rec["source"])
        for family, nbytes in transfer.items():
            f = fams.setdefault(family, {
                "programs": 0, "dispatches": 0, "tokens": 0,
                "flops": 0.0, "bytes": 0.0, "out_bytes": 0.0,
                "sources": set(),
            })
            f["bytes"] += nbytes
            f["sources"].add("transfer")
        out_families: dict = {}
        covered_wall = 0.0
        for family, f in fams.items():
            wall = float((device_s or {}).get(family, 0.0))
            if f["dispatches"] > 0 and wall > 0:
                covered_wall += wall
            intensity = f["flops"] / f["bytes"] if f["bytes"] > 0 else None
            verdict = None
            if intensity is not None and (f["flops"] > 0 or f["bytes"] > 0):
                verdict = (
                    "memory_bound" if intensity < ridge else "compute_bound"
                )
            entry = {
                "programs": f["programs"],
                "dispatches": f["dispatches"],
                "tokens": f["tokens"],
                "flops": f["flops"],
                "bytes": f["bytes"],
                "out_bytes": f["out_bytes"],
                "wall_s": round(wall, 4),
                "achieved_flops_per_s": (
                    f["flops"] / wall if wall > 0 else None
                ),
                "achieved_bytes_per_s": (
                    f["bytes"] / wall if wall > 0 else None
                ),
                "intensity": intensity,
                "verdict": verdict,
                "source": "+".join(sorted(f["sources"])) or "none",
            }
            if peak_f and wall > 0:
                entry["mfu_vs_peak"] = f["flops"] / wall / (peak_f * n_dev)
            if peak_b and wall > 0:
                entry["mbu_vs_peak"] = f["bytes"] / wall / (peak_b * n_dev)
            out_families[family] = entry
        total_wall = sum(
            float(v) for v in (device_s or {}).values()
        )
        crosscheck: dict = {}
        for family, (lo, hi) in sorted(modeled_fpt.items()):
            f = fams.get(family)
            if not f or f["tokens"] <= 0 or f["flops"] <= 0:
                continue
            measured = f["flops"] / f["tokens"]
            ratio = measured / hi if measured > hi else (
                measured / lo if measured < lo else 1.0
            )
            entry = {
                "flops_per_token_xla": measured,
                "flops_per_token_modeled": [lo, hi],
                "ratio": round(ratio, 4),
                "ok": (1.0 / self.tol) <= ratio <= self.tol,
            }
            b = modeled_bpt.get(family)
            if b is not None and f["bytes"] > 0:
                entry["bytes_per_token_xla"] = f["bytes"] / f["tokens"]
                entry["bytes_per_token_modeled"] = list(b)
            crosscheck[family] = entry
        return {
            "ridge_flops_per_byte": round(ridge, 4),
            "ridge_source": ridge_source,
            "peak_flops_per_s": peak_f,
            "peak_bytes_per_s": peak_b,
            "n_devices": n_dev,
            "families": {
                k: _round_floats(v) for k, v in sorted(out_families.items())
            },
            "coverage": {
                "covered_wall_s": round(covered_wall, 4),
                "attrib_wall_s": round(total_wall, 4),
                "fraction": (
                    round(covered_wall / total_wall, 4)
                    if total_wall > 0 else None
                ),
            },
            "crosscheck": {
                k: _round_floats(v) for k, v in crosscheck.items()
            },
            "tol": self.tol,
        }

    def prom_families(self, device_s: Optional[dict] = None) -> dict:
        """The ``llmc_roofline_*`` families /metricsz renders. FLOPs /
        bytes / dispatch totals are COUNTERS (monotone, so the router's
        fleet merge sums them exactly like the attrib walls they join
        against); per-replica ratios (intensity, verdicts) deliberately
        stay off this surface — a gauge sum across replicas would be
        nonsense — scrapers derive fleet ratios from the counters, and
        the verdicts live on /statsz."""
        if device_s is None:
            device_s = self._attrib_walls()
        snap = self.snapshot(device_s)
        flops_samples = []
        bytes_samples = []
        disp_samples = []
        tok_samples = []
        for family, f in snap["families"].items():
            flops_samples.append(({"family": family}, f["flops"]))
            bytes_samples.append(({"family": family}, f["bytes"]))
            disp_samples.append(({"family": family}, f["dispatches"]))
            if f["tokens"]:
                tok_samples.append(({"family": family}, f["tokens"]))
        out = {
            "roofline_flops_total": {
                "type": "counter", "samples": flops_samples,
            },
            "roofline_bytes_total": {
                "type": "counter", "samples": bytes_samples,
            },
            "roofline_dispatches_total": {
                "type": "counter", "samples": disp_samples,
            },
            "roofline_tokens_total": {
                "type": "counter", "samples": tok_samples,
            },
            "roofline_ridge_flops_per_byte": {
                "type": "gauge",
                "samples": [
                    ({"source": snap["ridge_source"]},
                     snap["ridge_flops_per_byte"]),
                ],
            },
        }
        return out

    @staticmethod
    def _attrib_walls() -> dict:
        from llm_consensus_tpu.obs import attrib as attrib_mod

        led = attrib_mod.ledger()
        if led is None:
            return {}
        try:
            return led.snapshot()["device_s"]
        except Exception:  # noqa: BLE001
            return {}

    def counter_track(self) -> "list[tuple[str, float]]":
        """(counter name, value) pairs for the exported Perfetto trace's
        roofline counter track (obs/export.py ``ph: "C"`` events)."""
        snap = self.snapshot()
        out = []
        for family, f in snap["families"].items():
            out.append((f"roofline_flops/{family}", f["flops"]))
            out.append((f"roofline_bytes/{family}", f["bytes"]))
        return out


def _round_floats(doc: dict) -> dict:
    out = {}
    for k, v in doc.items():
        if isinstance(v, float):
            out[k] = round(v, 4) if abs(v) < 1e6 else v
        else:
            out[k] = v
    return out


# -- dispatch-site instrumentation -------------------------------------------


def instrument(fn, family: Optional[str] = None,
               key: Optional[Callable] = None,
               tokens: Optional[Callable] = None,
               steps: Optional[Callable] = None):
    """Wrap a jitted ``fn`` so dispatches book into the roofline ledger.

    ``family`` is the fallback program family; the thread's ambient
    attribution tag wins when set (``_copy_blocks`` serves kv_gather AND
    kv_publish, ``_decode_chunk`` serves decode AND draft — the tag at
    the dispatch site is the truth). ``key(args, kwargs)`` returns the
    hashable bucket-shape key (one static-cost capture per distinct
    value); ``tokens(args, kwargs)`` the tokens this dispatch advances
    (cross-check denominators); ``steps(args, kwargs)`` the on-device
    loop trip count XLA's cost analysis counts only once.

    Disabled (ledger None) the wrapper is one None check; the wrapped
    callable is signature- and attribute-transparent (``.lower`` etc.
    delegate to the jitted original).
    """

    @wraps(fn)
    def call(*args, **kwargs):
        led = ledger()
        if led is not None:
            try:
                from llm_consensus_tpu.obs import attrib as attrib_mod

                fam = attrib_mod.current_family() or family or "other"
                k = key(args, kwargs) if key is not None else _SENTINEL_KEY
                n_tok = int(tokens(args, kwargs)) if tokens is not None else 0
                n_steps = int(steps(args, kwargs)) if steps is not None else 1
                led.dispatch(fam, k, fn, args, kwargs,
                             tokens=n_tok, steps=max(1, n_steps))
            except Exception:  # noqa: BLE001 — telemetry never raises
                pass
        return fn(*args, **kwargs)

    call.__wrapped__ = fn
    for attr in ("lower", "trace", "eval_shape", "clear_cache",
                 "_cache_size"):
        if hasattr(fn, attr):
            setattr(call, attr, getattr(fn, attr))
    return call


def shape_of(x) -> tuple:
    """A cheap hashable bucket key component: the arg's shape, or the
    value itself for plain scalars/statics."""
    shape = getattr(x, "shape", None)
    if shape is not None:
        return tuple(shape)
    return (x,) if isinstance(x, (int, float, bool, str)) else ()


# -- process-wide resolution (the faults/obs binding pattern) -----------------

_lock = sanitizer.make_lock("obs.roofline.registry")
_ledger: Optional[RooflineLedger] = None
_resolved = False
_tls = threading.local()


def ledger() -> Optional[RooflineLedger]:
    """The process-wide roofline ledger, or None when disabled.

    ``LLMC_ROOFLINE=0`` disables; ``=1`` forces on; unset, roofline
    follows chip-time attribution (LLMC_ATTRIB / LLMC_LIVE) — the walls
    it joins against come from that ledger, so the two share one
    serving-observability budget."""
    global _ledger, _resolved
    if not _resolved:
        # Re-entrancy guard: resolving consults attrib.ledger(), and a
        # roofline-instrumented dispatch can occur while attrib itself
        # resolves; the nested call sees disabled rather than deadlock.
        if getattr(_tls, "resolving", False):
            return None
        with _lock:
            if not _resolved:
                _tls.resolving = True
                try:
                    env = knobs.get_str("LLMC_ROOFLINE")
                    if env == "0":
                        enabled = False
                    elif env:
                        enabled = True
                    else:
                        from llm_consensus_tpu.obs import attrib as attrib_mod

                        enabled = attrib_mod.ledger() is not None
                    if enabled:
                        _ledger = RooflineLedger()
                    _resolved = True
                finally:
                    _tls.resolving = False
    return _ledger


def install(led: Optional[RooflineLedger]) -> None:
    """Install ``led`` as the process ledger (tests / CLI flags)."""
    global _ledger, _resolved
    with _lock:
        _ledger = led
        _resolved = True


def reset() -> None:
    """Forget the cached ledger; the next :func:`ledger` re-reads env."""
    global _ledger, _resolved
    with _lock:
        _ledger = None
        _resolved = False


__all__ = [
    "DEFAULT_RIDGE", "DEFAULT_TOL", "RooflineLedger", "install",
    "instrument", "ledger", "note_modeled", "reset", "shape_of",
]


def note_modeled(family: str, flops_per_token: float,
                 bytes_per_token: Optional[float] = None) -> None:
    """Module-level convenience: register modeled per-token costs with
    the installed ledger (no-op when roofline is off)."""
    led = ledger()
    if led is not None:
        led.note_modeled(family, flops_per_token, bytes_per_token)
