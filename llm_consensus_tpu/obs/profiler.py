"""On-demand deep profiling: a bounded ``jax.profiler`` trace window.

The roofline plane (obs/roofline.py) answers "which family, how far from
which roof" continuously and for free; when a family's achieved FLOPs/s
says something is wrong, the next question — WHICH fusion, WHICH
transfer, WHAT overlap — needs the real profiler. This module arms one
``jax.profiler.start_trace``/``stop_trace`` window on demand
(``POST /debugz/profile``, the router fan-out, or ``--profile`` on
one-shot CLI runs) with the blackbox plane's safety rails:

  * **bounded** — the window stops itself after ``duration_s`` (clamped
    to ``LLMC_PROFILE_MAX_S``) on a daemon timer; a wedged caller can
    not leave the profiler running forever.
  * **single-flight + rate-limited** — one window at a time, and at
    most one window start per ``LLMC_PROFILE_MIN_INTERVAL_S`` (XLA's
    profiler is process-global and NOT free; the 429 path exists so a
    crash-looping dashboard cannot turn the serving process into a
    permanent profiling session).
  * **atomic artifact dir** — the trace lands in ``<final>.partial``
    and is renamed to ``<final>`` only after ``stop_trace`` returns, so
    a consumer that sees the directory sees a complete artifact.

Resolution follows the blackbox pattern: ``profiler()`` reads
``LLMC_PROFILE*`` once; ``install()``/``reset()`` rebind for tests and
dryrun lanes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.utils import knobs

# Under data/_artifacts/ — non-run telemetry namespace; the flywheel
# corpus scanner skips it wholesale (flywheel/corpus.py).
DEFAULT_DIR = os.path.join("data", "_artifacts", "profiles")
DEFAULT_MAX_S = 10.0
DEFAULT_MIN_INTERVAL_S = 60.0


class DeepProfiler:
    """Arms bounded ``jax.profiler`` trace windows; never raises."""

    def __init__(self, out_dir: Optional[str] = None,
                 max_s: Optional[float] = None,
                 min_interval_s: Optional[float] = None):
        self.out_dir = out_dir or (
            knobs.get_str("LLMC_PROFILE_DIR") or DEFAULT_DIR
        )
        self.max_s = max_s if max_s is not None else knobs.get_float(
            "LLMC_PROFILE_MAX_S", DEFAULT_MAX_S
        )
        self.min_interval_s = (
            min_interval_s if min_interval_s is not None
            else knobs.get_float(
                "LLMC_PROFILE_MIN_INTERVAL_S", DEFAULT_MIN_INTERVAL_S
            )
        )
        self._lock = sanitizer.make_lock("obs.profiler")
        self._active = False
        self._closing = False
        self._last_start = 0.0
        self._timer: Optional[threading.Timer] = None
        self.windows = 0
        self.suppressed = 0
        self.failed = 0
        self.last_path: Optional[str] = None
        self.last_duration_s: Optional[float] = None
        self.last_error: Optional[str] = None

    # -- the window -----------------------------------------------------------

    def arm(self, duration_s: Optional[float] = None,
            tag: str = "ondemand") -> "tuple[Optional[str], str]":
        """Start one bounded window; returns ``(final_path, status)``.

        ``status`` is ``"armed"`` (the artifact dir will appear at
        ``final_path`` when the window closes), ``"busy"`` /
        ``"rate_limited"`` (the HTTP layer's 429s), or ``"failed"``.
        """
        dur = float(duration_s) if duration_s else self.max_s
        dur = max(0.05, min(dur, self.max_s))
        with self._lock:
            if self._active:
                self.suppressed += 1
                return None, "busy"
            now = time.monotonic()
            if self.windows > 0 and (
                now - self._last_start < self.min_interval_s
            ):
                self.suppressed += 1
                return None, "rate_limited"
            # Reserve the window under the lock; a concurrent arm sees
            # busy, not a second start_trace on XLA's global profiler.
            self._active = True
            self._last_start = now
        safe = "".join(
            c if c.isalnum() or c in "-_" else "-" for c in str(tag)
        )[:32] or "ondemand"
        final = os.path.join(
            self.out_dir, f"profile-{safe}-{time.time_ns()}"
        )
        partial = final + ".partial"
        try:
            import jax

            os.makedirs(partial, exist_ok=True)
            jax.profiler.start_trace(partial)
        except Exception as e:  # noqa: BLE001 — telemetry never raises
            with self._lock:
                self._active = False
                self.failed += 1
                self.last_error = f"{type(e).__name__}: {e}"[:200]
            return None, "failed"
        t = threading.Timer(dur, self._finish, args=(partial, final, dur))
        t.daemon = True
        with self._lock:
            self._timer = t
        t.start()
        return final, "armed"

    def _finish(self, partial: str, final: str, dur: float) -> None:
        with self._lock:
            # One closer per window: the bound timer and an explicit
            # stop_now() may race — first claim wins, the loser no-ops
            # (a second stop_trace would raise into failure counters).
            if not self._active or self._closing:
                return
            self._closing = True
        try:
            import jax

            jax.profiler.stop_trace()
            os.replace(partial, final)
            with self._lock:
                self.windows += 1
                self.last_path = final
                self.last_duration_s = dur
        except Exception as e:  # noqa: BLE001
            with self._lock:
                self.failed += 1
                self.last_error = f"{type(e).__name__}: {e}"[:200]
        finally:
            with self._lock:
                self._active = False
                self._closing = False
                self._timer = None

    def stop_now(self) -> Optional[str]:
        """Close the in-flight window immediately (the CLI's --profile
        closes at end-of-run instead of waiting out the cap); returns
        the artifact path, or None when no window was open."""
        with self._lock:
            t = self._timer
            if not self._active or t is None:
                return None
        t.cancel()
        self._finish(*t.args)
        with self._lock:
            return self.last_path

    def wait(self, timeout_s: float = 30.0) -> bool:
        """Block until the in-flight window (if any) closes; True when
        idle. For the CLI's ``--profile`` and the dryrun lane."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                t = self._timer
                active = self._active
            if not active:
                return True
            if t is not None:
                t.join(timeout=min(1.0, deadline - time.monotonic()))
            else:
                time.sleep(0.02)
        with self._lock:
            return not self._active

    def active(self) -> bool:
        with self._lock:
            return self._active

    def stats(self) -> dict:
        with self._lock:
            return {
                "active": self._active,
                "windows": self.windows,
                "suppressed": self.suppressed,
                "failed": self.failed,
                "max_s": self.max_s,
                "min_interval_s": self.min_interval_s,
                "last_path": self.last_path,
                "last_duration_s": self.last_duration_s,
                "last_error": self.last_error,
            }


# -- process-wide resolution (the faults/obs binding pattern) ----------------

_lock = sanitizer.make_lock("obs.profiler.registry")
_profiler: Optional[DeepProfiler] = None
_resolved = False


def profiler() -> Optional[DeepProfiler]:
    """The process-wide deep profiler, or None when ``LLMC_PROFILE=0``.
    Resolved once; consumers bind at construction time."""
    global _profiler, _resolved
    if not _resolved:
        with _lock:
            if not _resolved:
                if knobs.get_bool("LLMC_PROFILE"):
                    _profiler = DeepProfiler()
                _resolved = True
    return _profiler


def install(p: Optional[DeepProfiler]) -> None:
    """Install ``p`` as the process profiler (tests / CLI / dryrun)."""
    global _profiler, _resolved
    with _lock:
        _profiler = p
        _resolved = True


def reset() -> None:
    """Forget the cached profiler; the next :func:`profiler` re-reads
    the environment."""
    global _profiler, _resolved
    with _lock:
        _profiler = None
        _resolved = False


__all__ = [
    "DEFAULT_DIR", "DEFAULT_MAX_S", "DEFAULT_MIN_INTERVAL_S",
    "DeepProfiler", "install", "profiler", "reset",
]
