"""The always-on flight recorder: a bounded ring of recent spans.

The per-run :class:`~llm_consensus_tpu.obs.recorder.Recorder` is opt-in
(``--events``) and run-scoped: when an engine crashes at 3 a.m. with
events off, the timeline that would explain it was never recorded. The
:class:`FlightRecorder` closes that gap the way an aircraft blackbox
does — a fixed-size ring (``LLMC_BLACKBOX_EVENTS``, default 4096) of the
most recent spans and instants from the hot subsystems (batcher decode/
fetch/admit, engine streams, gateway requests, governor transitions),
recording ALWAYS (``LLMC_BLACKBOX=0`` opts out), costing one deque
append per event and a bounded, pre-allocated memory ceiling.

On an anomaly the ring **dumps**: a Perfetto-loadable Chrome-trace
snapshot written atomically to ``LLMC_BLACKBOX_DIR`` (default
``data/_artifacts/blackbox/``) carrying the seconds of activity BEFORE the trigger
— the part of the timeline post-hoc tooling can never recover. Triggers:

  * **engine crash / wedge** — the batcher's pool-fatal exception path
    and the supervisor's wedge watchdog (recovery/supervisor.py);
  * **pressure escalation past ``preempt``** — the governor reaching
    brownout or shed (pressure/governor.py): user-visible degradation
    started, snapshot why;
  * **SLO burn** — p99 TTFT over ``LLMC_SLO_TTFT_P99_S`` for
    ``LLMC_SLO_WINDOWS`` consecutive live-metrics windows
    (obs/live.SLOWatcher, wired by the gateway).

Dumps are rate-limited (``LLMC_BLACKBOX_MIN_INTERVAL_S``, default 30 s)
so a crash-looping pool costs one snapshot per interval, not one per
restart attempt.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.obs.recorder import Event
from llm_consensus_tpu.utils import knobs

DEFAULT_CAPACITY = 4096
DEFAULT_MIN_INTERVAL_S = 30.0
# Under data/_artifacts/: the corpus scanner (flywheel/corpus.py) treats
# everything below that namespace as non-run telemetry, so dumps never
# collide with run-id dirs or trip the manifest-validation counters.
DEFAULT_DIR = os.path.join("data", "_artifacts", "blackbox")


class FlightRecorder:
    """Bounded ring of recent Events + anomaly-triggered trace dumps.

    Recording is lock-free on the hot path (``deque.append`` with a
    maxlen is atomic under the GIL); only ``dump``/``snapshot`` take the
    lock, and only dump's rate-limit state needs it.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 out_dir: str = DEFAULT_DIR,
                 min_interval_s: float = DEFAULT_MIN_INTERVAL_S):
        self._ring: deque = deque(maxlen=max(16, capacity))
        self.out_dir = out_dir
        self.min_interval_s = min_interval_s
        self._lock = sanitizer.make_lock("obs.blackbox")
        self._last_dump = 0.0
        self.dumps = 0
        self.suppressed = 0
        self.last_reason: Optional[str] = None
        self.last_path: Optional[str] = None

    # -- recording (hot path) ------------------------------------------------

    @staticmethod
    def now() -> int:
        return time.monotonic_ns()

    def complete(self, name: str, t0_ns: int, tid: str = "main",
                 **args) -> None:
        """Record a span that started at ``t0_ns`` and ends now — the
        same hot-path shape Recorder.complete has."""
        t1 = time.monotonic_ns()
        self._ring.append(Event(
            name=name, ph="X", ts_ns=t0_ns, tid=tid,
            dur_ns=max(t1 - t0_ns, 0), args=args,
        ))

    def instant(self, name: str, tid: str = "main", **args) -> None:
        self._ring.append(Event(
            name=name, ph="i", ts_ns=time.monotonic_ns(), tid=tid, args=args,
        ))

    # -- reading / dumping ---------------------------------------------------

    def snapshot(self) -> list:
        """The ring's events, oldest first (a consistent copy)."""
        return list(self._ring)

    def depth(self) -> int:
        return len(self._ring)

    def dump(self, reason: str, extra: Optional[dict] = None,
             force: bool = False) -> Optional[str]:
        """Write the ring as a Perfetto-loadable trace; returns the path
        (None when rate-limited, empty, or the write failed — a blackbox
        must never fail the system it is recording)."""
        try:
            events = list(self._ring)
            if not events:
                return None  # nothing captured: touch no dump state
            with self._lock:
                now = time.monotonic()
                if not force and (
                    now - self._last_dump < self.min_interval_s
                    and self.dumps > 0
                ):
                    self.suppressed += 1
                    return None
                # Reserve the rate-limit window now (a concurrent
                # trigger must not race a second dump of the same ring).
                prev_last = self._last_dump
                self._last_dump = now
            from llm_consensus_tpu.obs.export import (
                chrome_events, trace_document)
            from llm_consensus_tpu.output.persist import save_file

            doc = trace_document(
                chrome_events(events, pid=0, process_name="blackbox")
            )
            doc["blackbox"] = {
                "reason": reason,
                "events": len(events),
                "dumped_unix": time.time(),
                **(extra or {}),
            }
            name = f"blackbox-{_safe(reason)}-{time.time_ns()}.json"
            path = save_file(
                self.out_dir, name, json.dumps(doc, indent=2) + "\n"
            )
            with self._lock:
                if path is None:
                    # Nothing landed on disk: release the window so the
                    # NEXT anomaly retries, and leave dumps/last_* naming
                    # the last dump that actually exists.
                    self._last_dump = prev_last
                    return None
                self.dumps += 1
                self.last_reason = reason
                self.last_path = path
            return path
        except Exception:  # noqa: BLE001
            return None

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": len(self._ring),
                "capacity": self._ring.maxlen,
                "dumps": self.dumps,
                "suppressed": self.suppressed,
                "last_reason": self.last_reason,
                "last_path": self.last_path,
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._last_dump = 0.0
            self.dumps = 0
            self.suppressed = 0
            self.last_reason = None
            self.last_path = None


def _safe(reason: str) -> str:
    return "".join(
        c if c.isalnum() or c in "-_" else "-" for c in str(reason)
    )[:48] or "anomaly"


# -- process-wide resolution (the faults/obs binding pattern) ----------------

_lock = sanitizer.make_lock("obs.blackbox.registry")
_ring: Optional[FlightRecorder] = None
_resolved = False


def _resolve() -> Optional[FlightRecorder]:
    if not knobs.get_bool("LLMC_BLACKBOX"):
        return None
    capacity = knobs.get_int("LLMC_BLACKBOX_EVENTS", DEFAULT_CAPACITY)
    interval = knobs.get_float(
        "LLMC_BLACKBOX_MIN_INTERVAL_S", DEFAULT_MIN_INTERVAL_S
    )
    out_dir = knobs.get_str("LLMC_BLACKBOX_DIR") or DEFAULT_DIR
    return FlightRecorder(
        capacity=capacity, out_dir=out_dir, min_interval_s=interval
    )


def ring() -> Optional[FlightRecorder]:
    """The process-wide flight recorder, or None when ``LLMC_BLACKBOX=0``.
    Resolved once; consumers bind at construction time."""
    global _ring, _resolved
    if not _resolved:
        with _lock:
            if not _resolved:
                _ring = _resolve()
                _resolved = True
    return _ring


def install(r: Optional[FlightRecorder]) -> None:
    """Install ``r`` as the process flight recorder (tests / CLI)."""
    global _ring, _resolved
    with _lock:
        _ring = r
        _resolved = True


def reset() -> None:
    """Forget the cached ring; the next :func:`ring` re-reads env."""
    global _ring, _resolved
    with _lock:
        _ring = None
        _resolved = False


__all__ = [
    "DEFAULT_CAPACITY", "DEFAULT_DIR", "DEFAULT_MIN_INTERVAL_S",
    "FlightRecorder", "install", "reset", "ring",
]
