"""Merge per-controller timelines into one Chrome trace.

Under multi-controller execution every process records its own timeline on
its own ``time.monotonic_ns`` clock. This module exchanges the serialized
events over the SAME bounded allgather the result merge uses
(parallel/multicontroller.allgather_json_bounded), so the merge inherits
the run's degraded-mode semantics for free: a dead or already-degraded
peer costs its timeline, not the merge — the survivors' events still
produce a loadable trace, and nothing ever hangs on a peer whose liveness
is unknowable.

Clock alignment: monotonic clocks have arbitrary per-process origins, so
each payload carries the sender's clock reading taken at payload build —
immediately before entering the collective. Processes enter the gather
together (the collective is the barrier), so peer i's stamp and ours name
approximately the same wall instant; ``offset_i = t_mine − t_i`` maps
peer i's timestamps onto the local clock to within the barrier-entry skew
(micro- to milliseconds over ICI/DCN — enough to line up phase-level
spans, which is what the timeline is for; it is not a distributed-tracing
clock sync).
"""

from __future__ import annotations

from typing import Optional

from llm_consensus_tpu.obs.recorder import Event, Recorder

# Per-controller cap on events shipped through the merge exchange (the
# newest survive). Local traces are never truncated by this — only what
# rides the collective.
MERGE_MAX_EVENTS = 100_000


def _serialize(events: list[Event]) -> list[dict]:
    return [
        {
            "name": e.name, "ph": e.ph, "ts_ns": e.ts_ns, "tid": e.tid,
            "dur_ns": e.dur_ns, "args": e.args,
        }
        for e in events
    ]


def _deserialize(raw: list[dict]) -> list[Event]:
    return [
        Event(
            name=d["name"], ph=d["ph"], ts_ns=int(d["ts_ns"]),
            tid=d["tid"], dur_ns=int(d.get("dur_ns", 0)),
            args=d.get("args") or {},
        )
        for d in raw
    ]


def merge_timelines(
    recorder: Recorder, timeout: Optional[float] = None
) -> "tuple[dict, list[int]]":
    """Every reachable controller's timeline as ONE trace document.

    Returns ``(trace_document, missing)`` — ``missing`` lists controller
    indices whose timeline never arrived (the survivor-only merge). In a
    single-process run the exchange is the identity and the result equals
    :func:`obs.export.local_trace`.
    """
    from llm_consensus_tpu.obs import export
    from llm_consensus_tpu.parallel import multicontroller as mc

    me = mc.process_index()
    events = recorder.events()
    # Bound the exchanged payload: the gather rides the run's bounded
    # deadline, and a full LLMC_EVENTS_MAX timeline (~tens of MB of
    # JSON per controller) could miss it on a slow DCN — a truncated
    # tail beats a survivor-only merge. The newest events win (the
    # phases being debugged are usually the latest).
    truncated = max(len(events) - MERGE_MAX_EVENTS, 0)
    payload = {
        "pid": me,
        "clock_ns": Recorder.now(),
        "truncated": truncated,
        "events": _serialize(events[truncated:]),
    }
    parts, missing = mc.allgather_json_bounded(payload, timeout)

    local_clock = payload["clock_ns"]
    merged: list[tuple[int, int, list[Event]]] = []  # (pid, offset, events)
    for part in parts:
        if part is None:
            continue  # a controller that missed the deadline
        offset = local_clock - int(part["clock_ns"])
        merged.append((int(part["pid"]), offset, _deserialize(part["events"])))

    base = min(
        (e.ts_ns + off for _, off, evs in merged for e in evs),
        default=0,
    )
    trace_events: list[dict] = []
    for pid, offset, events in merged:
        trace_events.extend(export.chrome_events(
            events, pid=pid, clock_offset_ns=offset, base_ns=base,
        ))
    return export.trace_document(trace_events), missing
