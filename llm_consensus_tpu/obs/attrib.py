"""Chip-time attribution: where did this second of device time go?

PR 10 made the fleet's *request-level* state continuously visible; this
module makes the CHIP visible at runtime. Three questions, answered live
instead of post-hoc:

  * **Device-time attribution** — every dispatch interval the serving
    stack observes is booked against a program *family*
    (:data:`FAMILIES`): ``decode`` / ``spec_verify`` from the batcher's
    pure arrival intervals (device + transfer wall of exactly one chunk),
    ``prefill`` / ``compact`` from the impure intervals and the drained-
    pipeline admission walls, ``kv_gather`` / ``kv_publish`` from the
    paged pool's copy dispatches, ``allgather`` from the bounded
    multi-controller exchange, ``draft`` from single-stream model-draft
    rounds. Exported as ``llmc_device_time_seconds_total{family=…}``
    counters on ``/metricsz`` (bucket-wise mergeable on the router like
    every other counter) and as per-dispatch live histograms
    (``llmc_device_time_seconds{family=…}``), so live MFU/MBU per engine
    pool is a gauge, not a post-run artifact.
  * **Goodput ledger** — tokens are booked by *disposition*
    (:data:`DISPOSITIONS`): ``useful`` counts every token actually
    appended to a stream (exactly once — a preempted stream's replayed
    prefix was useful when first decoded and is booked ``preempt_replay``
    when re-prefilled), ``spec_rejected`` the verify positions a
    speculative round threw away, ``overshoot`` the dead-stepped slots of
    retired/evicted rows, ``abandoned`` the emitted tokens of streams a
    pool death failed, ``crash_replay`` / ``preempt_replay`` the prefixes
    re-prefilled by recovery / preemption, ``evicted_kv`` the pool tokens
    whose KV was published then dropped (the recompute exposure).
    ``llmc_tokens_total{disposition=…}`` plus a goodput fraction on
    ``/statsz``.
  * **Host gaps (bubbles)** — device idle between a drained dispatch
    pipeline and the next dispatch on a batcher that still has work,
    attributed to the scheduler phase that preceded the gap
    (``admit`` / ``establish`` / ``compact`` / ``absorb`` / ``preempt`` /
    ``resize`` / ``schedule``): ``llmc_host_gap_seconds_total{phase=…}``
    and a live histogram, the MPMD-style bubble accounting that makes a
    multi-program schedule debuggable.

Two sentinels feed the PR-10 flight recorder:

  * **Retrace sentinel** — a ``jax.monitoring`` listener attributes every
    XLA backend compile to the family the dispatching thread was tagged
    with (:func:`tag`). A compile AFTER warmup (``LLMC_ATTRIB_WARMUP_S``,
    default 120 s, or :meth:`ChipTimeLedger.mark_warm`) is a retrace-storm
    candidate: a warning instant lands in the recorder + blackbox ring and
    the ring dumps (reason ``retrace``, rate-limited by the recorder's own
    interval).
  * **HBM watermark** — modeled resident bytes (weights + KV-pool arena +
    batcher pool caches register themselves as components) plus real
    device memory stats where the backend reports them
    (``device.memory_stats()``: bytes_in_use / peak / limit). The paged
    pool calls :meth:`ChipTimeLedger.hbm_pressure` BEFORE its
    exhaustion-truncation path fires, so the high-water instant + blackbox
    dump precede the first silently-degraded publish.

Resolution follows the faults/obs/live zero-cost pattern:
:func:`ledger` resolves once (``LLMC_ATTRIB``; default follows the live
plane — ``LLMC_LIVE=0`` turns attribution off too unless ``LLMC_ATTRIB=1``
forces it) and consumers bind the result at construction. Hot-path cost:
one bound None-check per site, a lock + dict bump per *chunk* (not per
token — the per-token ``useful`` bump is one lock acquire in the Python
emit loop the live plane already gates at ≤2%).
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Optional
from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.utils import knobs

# Program families device time is booked against. "other" catches
# compiles fired outside any tagged dispatch (imports, warmup helpers).
# "kv_handoff" is the disaggregated-serving transfer family: the
# cross-mesh reshard (device_put) of finished prefix KV from a prefill
# worker's mesh into the decode pool's arena (engine/handoff.py).
# "elastic" books fleet-transition work: runtime prefill/decode
# re-carves (TPUProvider.replan_disagg) and any compile they force.
# "swap" books hot-swap work: sharding/quantizing an incoming weight
# version (Engine.swap_weights) and the flip itself. "train_step" books
# the flywheel's distillation steps when a ledger is live in-process.
FAMILIES = (
    "prefill", "decode", "spec_verify", "draft",
    "kv_gather", "kv_publish", "kv_handoff", "allgather", "compact",
    "elastic", "swap", "train_step",
    "other",
)

# Token dispositions of the goodput ledger. "useful" is exact by
# construction: one bump per token APPENDED to a stream, nowhere else.
DISPOSITIONS = (
    "useful", "preempt_replay", "crash_replay", "spec_rejected",
    "overshoot", "abandoned", "evicted_kv",
)

# Scheduler phases a host gap (device bubble) can be attributed to.
GAP_PHASES = (
    "admit", "establish", "compact", "absorb", "preempt", "resize",
    "schedule",
)

DEFAULT_WARMUP_S = 120.0
DEFAULT_HBM_HIGH = 0.92

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# Thread-local program-family tag: the retrace listener reads it to
# attribute a compile to whatever the dispatching thread was doing.
_tls = threading.local()


@contextmanager
def tag(family: str):
    """Tag this thread's dispatches with a program family for the
    duration — the retrace sentinel's attribution source. Cheap enough
    to run unconditionally (two attribute writes), so call sites don't
    need a ledger-bound guard around the ``with``."""
    prev = getattr(_tls, "family", None)
    _tls.family = family
    try:
        yield
    finally:
        _tls.family = prev


def current_family() -> Optional[str]:
    return getattr(_tls, "family", None)


class ChipTimeLedger:
    """Process-wide device-time / goodput / gap / sentinel accounting.

    Thread-safe: one lock serializes every counter write; reads snapshot
    under the same lock. Histogram observations go to the live plane
    (obs/live) when it is enabled, so windowed quantiles ride the
    existing rotation machinery for free.
    """

    def __init__(self, warmup_s: Optional[float] = None,
                 hbm_high: Optional[float] = None):
        if warmup_s is None:
            warmup_s = knobs.get_float("LLMC_ATTRIB_WARMUP_S", DEFAULT_WARMUP_S)
        if hbm_high is None:
            hbm_high = knobs.get_float("LLMC_ATTRIB_HBM_HIGH", DEFAULT_HBM_HIGH)
        self.warmup_s = max(0.0, warmup_s)
        self.hbm_high = min(1.0, max(0.0, hbm_high))
        self._t0 = time.monotonic()
        self._lock = sanitizer.make_lock("obs.attrib")
        self._device_s: dict = {}
        self._dispatches: dict = {}
        self._tokens: dict = {}
        self._gap_s: dict = {}
        self._gaps = 0
        # Retrace sentinel state.
        self._compiles: dict = {}
        self._compile_s: dict = {}
        self._retraces = 0
        self._warm_marked = False
        # HBM watermark state.
        self._components: dict = {}
        self._peak_modeled = 0
        self._hbm_events = 0

    # -- device-time attribution ---------------------------------------------

    def observe_device(self, family: str, seconds: float,
                       dispatches: int = 1) -> None:
        """Book ``seconds`` of observed device/transfer wall against
        ``family`` and feed the live per-dispatch histogram. Never
        raises — attribution must not fail the dispatch it measures."""
        try:
            seconds = float(seconds)
            if seconds < 0:
                seconds = 0.0
            with self._lock:
                self._device_s[family] = (
                    self._device_s.get(family, 0.0) + seconds
                )
                self._dispatches[family] = (
                    self._dispatches.get(family, 0) + dispatches
                )
            live = _live()
            if live is not None:
                live.observe("device_time", seconds, family=family)
        except Exception:  # noqa: BLE001
            pass

    # -- goodput ledger -------------------------------------------------------

    def token_event(self, disposition: str, n: int = 1) -> None:
        """Book ``n`` tokens under ``disposition`` (see DISPOSITIONS)."""
        if n <= 0:
            return
        with self._lock:
            self._tokens[disposition] = self._tokens.get(disposition, 0) + n

    # -- host gaps (bubbles) --------------------------------------------------

    def gap(self, seconds: float, phase: str = "schedule") -> None:
        """Book one device-idle bubble on a busy batcher, attributed to
        the scheduler phase that preceded the dispatch that ended it."""
        try:
            seconds = float(seconds)
            if seconds <= 0:
                return
            with self._lock:
                self._gap_s[phase] = self._gap_s.get(phase, 0.0) + seconds
                self._gaps += 1
            live = _live()
            if live is not None:
                live.observe("host_gap", seconds, phase=phase)
        except Exception:  # noqa: BLE001
            pass

    # -- retrace sentinel -----------------------------------------------------

    @property
    def warmed(self) -> bool:
        """Past warmup: a compile from here on is a retrace candidate."""
        return self._warm_marked or (
            time.monotonic() - self._t0 > self.warmup_s
        )

    def mark_warm(self) -> None:
        """Declare warmup over NOW (serving steady state reached)."""
        self._warm_marked = True

    def _note_compile(self, duration_s: float) -> None:
        """One XLA backend compile happened on this thread (called from
        the jax.monitoring listener). Attribute it to the thread's tagged
        family; past warmup, fire the retrace sentinel."""
        family = current_family() or "other"
        warmed = self.warmed
        with self._lock:
            self._compiles[family] = self._compiles.get(family, 0) + 1
            self._compile_s[family] = (
                self._compile_s.get(family, 0.0) + float(duration_s)
            )
            if warmed:
                self._retraces += 1
        if not warmed:
            return
        info = {
            "family": family,
            "compile_s": round(float(duration_s), 4),
            "retraces": self._retraces,
        }
        try:
            from llm_consensus_tpu import obs as _obs

            rec = _obs.recorder()
            if rec is not None:
                rec.instant("retrace", tid="attrib", **info)
                rec.count("attrib.retraces")
            bb = _obs.blackbox.ring()
            if bb is not None:
                bb.instant("retrace", tid="attrib", **info)
                # A post-warmup compile inside serving traffic is exactly
                # the timeline the blackbox exists for: what dispatched
                # with what shapes right before the compile. Rate-limited
                # by the recorder's own interval (a storm costs one dump
                # per interval, not one per compile).
                bb.dump("retrace", extra=info)
        except Exception:  # noqa: BLE001
            pass

    # -- HBM watermark --------------------------------------------------------

    def update_component(self, name: str, nbytes: int) -> None:
        """Register/refresh one modeled resident-HBM component (weights,
        KV-pool arena, a batcher's pool cache). The modeled sum is the
        CPU-runnable stand-in for device memory stats."""
        with self._lock:
            self._components[name] = int(nbytes)
            total = sum(self._components.values())
            if total > self._peak_modeled:
                self._peak_modeled = total

    def hbm_device_stats(self) -> Optional[dict]:
        """Real allocator stats where the backend reports them (TPU/GPU);
        None on CPU. Worst device wins — exhaustion is per-chip."""
        try:
            import jax

            worst = None
            for d in jax.local_devices():
                try:
                    st = d.memory_stats()
                except Exception:  # noqa: BLE001
                    st = None
                if not st or not st.get("bytes_limit"):
                    continue
                frac = st.get("bytes_in_use", 0) / st["bytes_limit"]
                if worst is None or frac > worst["frac"]:
                    worst = {
                        "bytes_in_use": int(st.get("bytes_in_use", 0)),
                        "peak_bytes_in_use": int(
                            st.get("peak_bytes_in_use", 0)
                        ),
                        "bytes_limit": int(st["bytes_limit"]),
                        "frac": round(frac, 4),
                    }
            return worst
        except Exception:  # noqa: BLE001
            return None

    def hbm_pressure(self, source: str, **info) -> None:
        """An HBM-pressure event (the KV pool about to truncate a
        publish, an allocator high-water crossing): warning instant into
        recorder + blackbox, then a rate-limited blackbox dump — BEFORE
        the degradation path it precedes fires."""
        with self._lock:
            self._hbm_events += 1
        payload = {"source": source, **info}
        dev = self.hbm_device_stats()
        if dev is not None:
            payload["hbm_frac"] = dev["frac"]
        try:
            from llm_consensus_tpu import obs as _obs

            rec = _obs.recorder()
            if rec is not None:
                rec.instant("hbm_high_water", tid="attrib", **payload)
                rec.count("attrib.hbm_events")
            bb = _obs.blackbox.ring()
            if bb is not None:
                bb.instant("hbm_high_water", tid="attrib", **payload)
                bb.dump("hbm_high_water", extra=payload)
        except Exception:  # noqa: BLE001
            pass

    # -- reading --------------------------------------------------------------

    def activity(self) -> int:
        """Monotone activity counter (dispatches + token events + gaps):
        the CLI's per-run watermark — did THIS run move the ledger."""
        with self._lock:
            return (
                sum(self._dispatches.values())
                + sum(self._tokens.values())
                + self._gaps
            )

    def snapshot(self) -> dict:
        """The /statsz ``attrib`` block: device time per family, goodput,
        gaps, compile/retrace counts, HBM watermark."""
        with self._lock:
            device_s = {
                k: round(v, 4) for k, v in sorted(self._device_s.items())
            }
            dispatches = dict(sorted(self._dispatches.items()))
            tokens = dict(sorted(self._tokens.items()))
            gap_s = {k: round(v, 4) for k, v in sorted(self._gap_s.items())}
            gaps = self._gaps
            compiles = dict(sorted(self._compiles.items()))
            compile_s = {
                k: round(v, 3) for k, v in sorted(self._compile_s.items())
            }
            retraces = self._retraces
            components = dict(sorted(self._components.items()))
            peak_modeled = self._peak_modeled
            hbm_events = self._hbm_events
        useful = tokens.get("useful", 0)
        wasted = sum(v for k, v in tokens.items() if k != "useful")
        hbm: dict = {
            "modeled_bytes": sum(components.values()),
            "peak_modeled_bytes": peak_modeled,
            "components": components,
            "events": hbm_events,
            "high_water_frac": self.hbm_high,
        }
        dev = self.hbm_device_stats()
        if dev is not None:
            hbm["device"] = dev
        return {
            "device_s": device_s,
            "busy_s": round(sum(device_s.values()), 4),
            "dispatches": dispatches,
            "tokens": tokens,
            "goodput": {
                "useful": useful,
                "wasted": wasted,
                "fraction": (
                    round(useful / (useful + wasted), 4)
                    if useful + wasted else None
                ),
            },
            "gap_s": gap_s,
            "gaps": gaps,
            "compiles": compiles,
            "compile_s": compile_s,
            "retraces": retraces,
            "warm": self.warmed,
            "hbm": hbm,
        }

    def prom_families(self) -> dict:
        """The labeled counter/gauge families /metricsz renders
        (obs/prom.render ``families=``). Counters merge bucket-wise on
        the router like every other llmc counter."""
        with self._lock:
            device = list(self._device_s.items())
            tokens = list(self._tokens.items())
            gaps = list(self._gap_s.items())
            compiles = list(self._compiles.items())
            retraces = self._retraces
            modeled = sum(self._components.values())
            peak = self._peak_modeled
        out: dict = {
            "device_time_seconds_total": {
                "type": "counter",
                "samples": [({"family": f}, s) for f, s in device],
            },
            "tokens_total": {
                "type": "counter",
                "samples": [({"disposition": d}, n) for d, n in tokens],
            },
            "host_gap_seconds_total": {
                "type": "counter",
                "samples": [({"phase": p}, s) for p, s in gaps],
            },
            "compiles_total": {
                "type": "counter",
                "samples": [({"family": f}, n) for f, n in compiles],
            },
            "retraces_total": {
                "type": "counter",
                "samples": [({}, retraces)],
            },
            # NOTE deliberately no goodput_fraction gauge here: the
            # router's fleet merge SUMS gauges per (name, labels), which
            # would render 3 replicas at 0.9 as a nonsense 2.7. The
            # fraction lives on /statsz; scrapers derive the fleet
            # fraction from the mergeable llmc_tokens_total counters.
            "hbm_modeled_bytes": {
                "type": "gauge",
                "samples": [
                    ({"kind": "live"}, modeled),
                    ({"kind": "peak"}, peak),
                ],
            },
        }
        dev = self.hbm_device_stats()
        if dev is not None:
            out["hbm_device_bytes"] = {
                "type": "gauge",
                "samples": [
                    ({"kind": "in_use"}, dev["bytes_in_use"]),
                    ({"kind": "peak"}, dev["peak_bytes_in_use"]),
                    ({"kind": "limit"}, dev["bytes_limit"]),
                ],
            }
        return out


def _live():
    """The live-metrics plane, resolved through the module accessor so
    a test-installed plane is always the one observed into."""
    try:
        from llm_consensus_tpu.obs import live as live_mod

        return live_mod.metrics()
    except Exception:  # noqa: BLE001
        return None


# -- jax.monitoring hookup (one listener per process, ever) -------------------

_listener_registered = False


def _on_jax_event(event: str, duration_s: float, **_kw) -> None:
    if event != _COMPILE_EVENT:
        return
    led = _ledger  # module global read: no lock on the listener path
    if led is not None:
        try:
            led._note_compile(duration_s)
        except Exception:  # noqa: BLE001
            pass


def _ensure_listener() -> None:
    """Register the compile listener ONCE per process; it forwards to
    whatever ledger is currently installed, so install()/reset() cycles
    (tests, the CLI flags) never stack listeners."""
    global _listener_registered
    if _listener_registered:
        return
    _listener_registered = True
    try:
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_on_jax_event)
    except Exception:  # noqa: BLE001
        pass


# -- process-wide resolution (the faults/obs binding pattern) -----------------

_lock = sanitizer.make_lock("obs.attrib.registry")
_ledger: Optional[ChipTimeLedger] = None
_resolved = False


def ledger() -> Optional[ChipTimeLedger]:
    """The process-wide attribution ledger, or None when disabled.

    ``LLMC_ATTRIB=0`` disables; unset, attribution follows the live
    plane (``LLMC_LIVE``) — the two are one serving-observability budget;
    ``LLMC_ATTRIB=1`` forces it on even with live histograms off."""
    global _ledger, _resolved
    if not _resolved:
        with _lock:
            if not _resolved:
                env = knobs.get_str("LLMC_ATTRIB")
                if env == "0":
                    enabled = False
                elif env:
                    enabled = True
                else:
                    enabled = knobs.get_bool("LLMC_LIVE")
                if enabled:
                    _ledger = ChipTimeLedger()
                    _ensure_listener()
                _resolved = True
    return _ledger


def install(led: Optional[ChipTimeLedger]) -> None:
    """Install ``led`` as the process ledger (tests / CLI flags)."""
    global _ledger, _resolved
    with _lock:
        _ledger = led
        _resolved = True
    if led is not None:
        _ensure_listener()


def reset() -> None:
    """Forget the cached ledger; the next :func:`ledger` re-reads env."""
    global _ledger, _resolved
    with _lock:
        _ledger = None
        _resolved = False


__all__ = [
    "DISPOSITIONS", "FAMILIES", "GAP_PHASES", "ChipTimeLedger",
    "current_family", "install", "ledger", "reset", "tag",
]
