"""Export a run's telemetry: Chrome trace-event JSON + metrics.json.

Two artifacts per run, persisted into ``data/<run-id>/`` next to
``result.json`` (output/persist.py):

  * ``trace.json`` — Chrome trace-event format (the JSON array-of-events
    form inside ``{"traceEvents": [...]}``), loadable in Perfetto
    (https://ui.perfetto.dev) or ``chrome://tracing``. ``pid`` is the
    controller process index (one row group per host under
    multi-controller execution, obs/multihost.py), ``tid`` the subsystem
    row ("engine", "batcher", "runner", ...). Timestamps are microseconds
    on the recorder's monotonic clock, rebased so the earliest event sits
    at t=0 — absolute wall time is in metrics.json, not the timeline.
  * ``metrics.json`` — the run's aggregate numbers: recorder counters,
    batcher phase-accounting snapshots, per-model token/throughput/MFU
    stats, the fault-injection decision trace, and degraded-mode /
    failed-model bookkeeping.

The trace-event fields follow the Trace Event Format spec: "X" complete
events carry ``dur``, "i" instants carry scope ``s`` ("t": thread), "M"
metadata names processes and threads.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Optional

from llm_consensus_tpu.obs.recorder import Event, Recorder

TRACE_FILE = "trace.json"
METRICS_FILE = "metrics.json"


def _tid_table(events: Iterable[Event]) -> dict[str, int]:
    """Stable subsystem-label → integer tid mapping (first-seen order
    would vary across thread interleavings; sorted names don't)."""
    return {name: i + 1 for i, name in enumerate(
        sorted({e.tid for e in events})
    )}


def chrome_events(
    events: list[Event],
    pid: int = 0,
    process_name: Optional[str] = None,
    clock_offset_ns: int = 0,
    base_ns: Optional[int] = None,
) -> list[dict]:
    """One process's events as trace-event dicts (metadata included).

    ``clock_offset_ns`` shifts this process's monotonic clock onto the
    merging host's (obs/multihost.py estimates it from the exchange);
    ``base_ns`` is the merged timeline's zero — defaults to this event
    list's earliest timestamp.
    """
    out: list[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": process_name or f"controller {pid}"},
    }]
    tids = _tid_table(events)
    for label, tid in tids.items():
        out.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": label},
        })
    if base_ns is None:
        base_ns = min((e.ts_ns for e in events), default=0) + clock_offset_ns
    for e in events:
        ts_us = (e.ts_ns + clock_offset_ns - base_ns) / 1e3
        d: dict = {
            "name": e.name, "ph": e.ph, "ts": ts_us,
            "pid": pid, "tid": tids[e.tid],
        }
        if e.ph == "X":
            d["dur"] = e.dur_ns / 1e3
        elif e.ph == "i":
            d["s"] = "t"
        if e.args:
            d["args"] = dict(e.args)
        out.append(d)
    return out


def trace_document(trace_events: list[dict]) -> dict:
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def roofline_counter_events(pid: int = 0, ts_us: float = 0.0) -> list[dict]:
    """The roofline ledger's cumulative FLOPs/bytes per family as "C"
    (counter) trace events — Perfetto renders each name as a counter
    track next to the span timeline, so "which family burned the FLOPs"
    reads off the same screen as "when". Empty when the plane is off."""
    from llm_consensus_tpu.obs import roofline as roofline_mod

    led = roofline_mod.ledger()
    if led is None:
        return []
    return [
        {
            "name": name, "ph": "C", "ts": ts_us, "pid": pid, "tid": 0,
            "args": {"value": value},
        }
        for name, value in led.counter_track()
    ]


def local_trace(recorder: Recorder, pid: int = 0) -> dict:
    """This process's timeline alone, as a loadable trace document
    (plus the roofline counter tracks when that plane is live)."""
    events = chrome_events(recorder.events(), pid=pid)
    end_us = max(
        (e.get("ts", 0.0) + e.get("dur", 0.0)
         for e in events if e.get("ph") != "M"),
        default=0.0,
    )
    events.extend(roofline_counter_events(pid=pid, ts_us=end_us))
    return trace_document(events)


def aggregate_throughput(
    recorder: Recorder, events: Optional[list[Event]] = None
) -> Optional[dict]:
    """Pool-wide decode throughput, or None when nothing was measured.

    Tokens over the UNION of the run's decode activity window (first
    decode dispatch to last fetch end on this recorder's timeline) —
    dividing by the SUM of per-stream decode walls would double-count
    concurrently-decoding streams/models and understate the pool rate by
    the concurrency factor. When no decode/fetch spans were recorded
    (counters-only recorders), falls back to the summed walls — correct
    for the sequential single-stream case they describe. MFU is the
    token-weighted mean of the per-response values. ``events`` lets a
    caller that already copied the event list (metrics_summary) avoid a
    second full copy under the recorder lock.
    """
    counters = recorder.counters()
    tokens = counters.get("decode_tokens", 0.0)
    if not tokens:
        return None
    if events is None:
        events = recorder.events()
    spans = [
        e for e in events if e.ph == "X" and e.name in ("decode", "fetch")
    ]
    if spans:
        window_s = (
            max(e.ts_ns + e.dur_ns for e in spans)
            - min(e.ts_ns for e in spans)
        ) / 1e9
    else:
        window_s = counters.get("decode_s", 0.0)
    if window_s <= 0:
        return None
    out = {
        "tokens": tokens,
        "tokens_per_sec": tokens / window_s,
        "window_s": window_s,
    }
    weighted = counters.get("mfu_weighted_tokens", 0.0)
    # Divide by the tokens that REPORTED an MFU, not all decode tokens —
    # a model whose chip has no known peak must not dilute the mean.
    mfu_tokens = counters.get("mfu_tokens", 0.0)
    if weighted and mfu_tokens:
        out["mfu"] = weighted / mfu_tokens
    return out


def _collect_provider_stats(registry, attr: str) -> dict:
    """Per-preset stats dicts merged from every distinct provider
    registered (providers repeat across models; dedup by identity),
    read via the provider method named ``attr``.

    Best-effort: a provider whose snapshot throws loses its entry, never
    the telemetry of a run that already produced its answer. Shared by
    the CLI's metrics export, the serve scheduler's per-run persistence,
    and the gateway's ``/statsz``.
    """
    out: dict = {}
    seen: set = set()
    for model in registry.models():
        provider = registry.get(model)
        if id(provider) in seen:
            continue
        seen.add(id(provider))
        stats_fn = getattr(provider, attr, None)
        if stats_fn is not None:
            try:
                out.update(stats_fn())
            except Exception:
                pass
    return out


def collect_batcher_stats(registry) -> dict:
    """Batcher phase-accounting snapshots, keyed by preset — see
    :func:`_collect_provider_stats` for the dedup/best-effort contract."""
    return _collect_provider_stats(registry, "batcher_stats")


def collect_disagg_stats(registry) -> dict:
    """Disaggregated prefill/decode handoff snapshots, keyed by preset
    (engine/handoff.py) — see :func:`_collect_provider_stats` for the
    dedup/best-effort contract."""
    return _collect_provider_stats(registry, "disagg_stats")


def collect_kv_stats(registry) -> dict:
    """Paged-KV-pool snapshots (kv/pool.KVPool.stats), keyed by preset —
    same contract as :func:`collect_batcher_stats`. Empty unless some
    live engine runs with LLMC_KV_POOL on."""
    return _collect_provider_stats(registry, "kv_stats")


def collect_spec_stats(registry) -> dict:
    """Speculative-decoding snapshots (TPUProvider.spec_stats: rounds,
    accepted tokens, acceptance EMA, governor state per preset) — same
    contract as :func:`collect_batcher_stats`. Empty unless a draft /
    spec decode mode is configured."""
    return _collect_provider_stats(registry, "spec_stats")


def live_summary(live=None) -> Optional[dict]:
    """Final quantiles of every live-histogram family (obs/live) as a
    JSON block: per (family, labels) count / sum / p50 / p90 / p99.

    The CLI-parity half of the live plane: a one-shot run's
    ``metrics.json`` carries the same per-family summary a serve-mode
    scrape would have shown, instead of losing the histograms at exit.
    Like a scrape, the summary is CUMULATIVE over the process (exact
    for one-shot runs; interactive/serving processes accumulate across
    runs — the per-run recorder, not this plane, owns run-scoped
    deltas). None when the plane is off or empty.
    """
    if live is None:
        from llm_consensus_tpu.obs import live as live_mod

        live = live_mod.metrics()
    if live is None:
        return None
    out: dict = {}
    for name, entries in sorted(live.families().items()):
        rows = []
        for labels, hist in sorted(
            entries, key=lambda lh: sorted(lh[0].items())
        ):
            if not hist.count:
                continue
            rows.append({
                "labels": dict(labels),
                "count": hist.count,
                "sum_s": round(hist.sum, 6),
                "p50_s": round(hist.quantile(0.5), 6),
                "p90_s": round(hist.quantile(0.9), 6),
                "p99_s": round(hist.quantile(0.99), 6),
            })
        if rows:
            out[name] = rows
    return out or None


def attrib_summary() -> Optional[dict]:
    """The chip-time attribution ledger's snapshot (obs/attrib), or None
    when the plane is off — metrics.json's ``attrib`` block."""
    from llm_consensus_tpu.obs import attrib as attrib_mod

    led = attrib_mod.ledger()
    return led.snapshot() if led is not None else None


def roofline_summary() -> Optional[dict]:
    """The roofline ledger's snapshot (obs/roofline: per-family static
    costs, achieved rates, bound verdicts, coverage, cross-check), or
    None when the plane is off or nothing dispatched — metrics.json's
    ``roofline`` block."""
    from llm_consensus_tpu.obs import roofline as roofline_mod

    led = roofline_mod.ledger()
    if led is None or led.activity() == 0:
        return None
    return led.snapshot()


def metrics_summary(
    recorder: Optional[Recorder] = None,
    responses=None,
    batcher_stats: Optional[dict] = None,
    kv_stats: Optional[dict] = None,
    spec_stats: Optional[dict] = None,
    disagg_stats: Optional[dict] = None,
    fault_trace: Optional[list[str]] = None,
    degraded_peers=None,
    failed_models: Optional[list[str]] = None,
    warnings: Optional[list[str]] = None,
    live: Optional[dict] = None,
    attrib: Optional[dict] = None,
    roofline: Optional[dict] = None,
) -> dict:
    """The run's aggregate numbers as one JSON-serializable dict.

    ``live`` / ``attrib`` / ``roofline`` carry the live-histogram
    summary (:func:`live_summary`), chip-time attribution snapshot
    (:func:`attrib_summary`), and roofline snapshot
    (:func:`roofline_summary`) when the caller collected them."""
    out: dict = {}
    if recorder is not None:
        events = recorder.events()  # one copy, shared with the aggregate
        out["counters"] = recorder.counters()
        out["events"] = {
            "recorded": len(events),
            "dropped": recorder.dropped,
        }
        agg = aggregate_throughput(recorder, events=events)
        if agg is not None:
            out["aggregate"] = agg
    if batcher_stats:
        out["batchers"] = batcher_stats
    if kv_stats:
        out["kv"] = kv_stats
    if spec_stats:
        out["spec"] = spec_stats
    if disagg_stats:
        out["disagg"] = disagg_stats
    if responses:
        out["models"] = [
            {
                k: v
                for k, v in (
                    ("model", r.model),
                    ("tokens", getattr(r, "tokens", None)),
                    ("tokens_per_sec", getattr(r, "tokens_per_sec", None)),
                    ("mfu", getattr(r, "mfu", None)),
                    ("mbu", getattr(r, "mbu", None)),
                    ("latency_ms", getattr(r, "latency_ms", None)),
                )
                if v is not None
            }
            for r in responses
        ]
    if live:
        out["live"] = live
    if attrib:
        out["attrib"] = attrib
    if roofline:
        out["roofline"] = roofline
    if fault_trace:
        out["faults"] = list(fault_trace)
    if degraded_peers:
        out["degraded_peers"] = sorted(int(p) for p in degraded_peers)
    if failed_models:
        out["failed_models"] = list(failed_models)
    if warnings:
        out["warnings"] = list(warnings)
    return out


def save_run_telemetry(
    run_dir: str,
    trace: dict,
    metrics: dict,
    warn=None,
) -> list[str]:
    """Write trace.json + metrics.json into ``run_dir`` (non-fatal on
    failure, like the other aux files — output/persist.save_aux_files)."""
    from llm_consensus_tpu.output.persist import save_file

    written = []
    for name, doc in ((TRACE_FILE, trace), (METRICS_FILE, metrics)):
        path = save_file(
            run_dir, name, json.dumps(doc, indent=2) + "\n", warn=warn
        )
        if path:
            written.append(path)
    return written


def load_trace(path: str) -> dict:
    """Parse a persisted trace (CI / tests gate on span presence)."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        raise ValueError(f"{os.path.basename(path)} is not a trace document")
    return doc


def trace_span_names(doc: dict) -> set[str]:
    return {
        e["name"] for e in doc["traceEvents"]
        if isinstance(e, dict) and e.get("ph") == "X"
    }
