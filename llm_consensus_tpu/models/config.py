"""Model family configurations.

One generic decoder-only transformer (models/transformer.py) covers every
family the framework serves — Llama-2/3, Mistral, Gemma, Qwen2, Mixtral —
via static config switches, so each (family, shape) pair compiles to a
single XLA program. The reference framework's "model set" is a table of
remote API names (/root/reference/cmd/llm-consensus/main.go:49-61); here the
catalog describes real on-device architectures.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional


@dataclass(frozen=True)
class ModelConfig:
    name: str
    family: str                     # llama | mistral | gemma | qwen2 | mixtral
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    head_dim: int
    d_ff: int
    rope_theta: float = 10000.0
    # Llama-3.1 NTK scaling: (factor, low_freq_factor, high_freq_factor,
    # original_max_position_embeddings); tuple so the config stays hashable.
    rope_scaling: Optional[tuple[float, float, float, int]] = None
    rms_eps: float = 1e-5
    activation: str = "silu"        # silu | gelu_tanh
    norm_offset: float = 0.0        # gemma: weights parameterized as (1 + w)
    embed_scale: bool = False       # gemma: embeddings scaled by sqrt(d_model)
    qkv_bias: bool = False          # qwen2
    sliding_window: Optional[int] = None  # mistral
    attn_logit_softcap: Optional[float] = None
    final_logit_softcap: Optional[float] = None
    tie_embeddings: bool = False
    n_experts: int = 0              # mixtral: 8
    experts_per_token: int = 0      # mixtral: 2
    max_seq_len: int = 8192

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @property
    def rope_scaling_dict(self) -> Optional[dict]:
        if self.rope_scaling is None:
            return None
        factor, low, high, orig = self.rope_scaling
        return {
            "factor": factor,
            "low_freq_factor": low,
            "high_freq_factor": high,
            "original_max_position_embeddings": orig,
        }

    def n_params(self, active_only: bool = False) -> int:
        """Exact parameter count (delegates to utils.flops — one formula,
        verified against ``init_params`` trees, serves the catalog, MFU
        accounting, and any future consumer)."""
        from llm_consensus_tpu.utils.flops import param_count

        return param_count(self, active_only=active_only)


_L = ModelConfig  # brevity in the table below

MODEL_PRESETS: dict[str, ModelConfig] = {c.name: c for c in [
    # -- Llama family ------------------------------------------------------
    _L("llama-2-7b", "llama", 32000, 4096, 32, 32, 32, 128, 11008,
       rope_theta=10000.0, max_seq_len=4096),
    _L("llama-3-8b", "llama", 128256, 4096, 32, 32, 8, 128, 14336,
       rope_theta=500000.0, max_seq_len=8192),
    _L("llama-3-70b", "llama", 128256, 8192, 80, 64, 8, 128, 28672,
       rope_theta=500000.0, max_seq_len=8192),
    _L("llama-3.1-8b", "llama", 128256, 4096, 32, 32, 8, 128, 14336,
       rope_theta=500000.0, rope_scaling=(8.0, 1.0, 4.0, 8192),
       max_seq_len=131072),
    # Llama 3.2: HF config.json dims; tied embeddings, 3.1-style rope scaling.
    _L("llama-3.2-1b", "llama", 128256, 2048, 16, 32, 8, 64, 8192,
       rope_theta=500000.0, rope_scaling=(32.0, 1.0, 4.0, 8192),
       tie_embeddings=True, max_seq_len=131072),
    _L("llama-3.2-3b", "llama", 128256, 3072, 28, 24, 8, 128, 8192,
       rope_theta=500000.0, rope_scaling=(32.0, 1.0, 4.0, 8192),
       tie_embeddings=True, max_seq_len=131072),
    # -- Mistral -----------------------------------------------------------
    _L("mistral-7b", "mistral", 32000, 4096, 32, 32, 8, 128, 14336,
       rope_theta=10000.0, sliding_window=4096, max_seq_len=32768),
    # -- Gemma -------------------------------------------------------------
    _L("gemma-7b", "gemma", 256000, 3072, 28, 16, 16, 256, 24576,
       rope_theta=10000.0, rms_eps=1e-6, activation="gelu_tanh",
       norm_offset=1.0, embed_scale=True, tie_embeddings=True),
    # -- Qwen2 -------------------------------------------------------------
    _L("qwen2-7b", "qwen2", 152064, 3584, 28, 28, 4, 128, 18944,
       rope_theta=1000000.0, rms_eps=1e-6, qkv_bias=True, max_seq_len=32768),
    _L("qwen2.5-7b", "qwen2", 152064, 3584, 28, 28, 4, 128, 18944,
       rope_theta=1000000.0, rms_eps=1e-6, qkv_bias=True, max_seq_len=131072),
    _L("qwen2.5-0.5b", "qwen2", 151936, 896, 24, 14, 2, 64, 4864,
       rope_theta=1000000.0, rms_eps=1e-6, qkv_bias=True,
       tie_embeddings=True, max_seq_len=32768),
    # -- Mixtral (MoE) -----------------------------------------------------
    _L("mixtral-8x7b", "mixtral", 32000, 4096, 32, 32, 8, 128, 14336,
       rope_theta=1000000.0, n_experts=8, experts_per_token=2,
       max_seq_len=32768),
    # -- Tiny variants: CI / CPU-mesh tests --------------------------------
    _L("tiny-llama", "llama", 512, 128, 2, 4, 2, 32, 256, max_seq_len=4096),
    _L("tiny-gemma", "gemma", 512, 128, 2, 4, 4, 32, 256, activation="gelu_tanh",
       norm_offset=1.0, embed_scale=True, tie_embeddings=True, max_seq_len=4096),
    _L("tiny-qwen2", "qwen2", 512, 128, 2, 4, 2, 32, 256, qkv_bias=True,
       max_seq_len=4096),
    _L("tiny-mistral", "mistral", 512, 128, 2, 4, 2, 32, 256,
       sliding_window=32, max_seq_len=4096),
    _L("tiny-mixtral", "mixtral", 512, 128, 2, 4, 2, 32, 256,
       n_experts=4, experts_per_token=2, max_seq_len=4096),
    # -- Bench sizes: single-chip demo scale (random-init) -----------------
    _L("consensus-1b", "llama", 32000, 2048, 16, 16, 8, 128, 5632,
       rope_theta=500000.0, max_seq_len=4096),
    _L("consensus-3b", "llama", 32000, 3072, 26, 24, 8, 128, 8192,
       rope_theta=500000.0, max_seq_len=4096),
]}


def get_config(name: str, **overrides) -> ModelConfig:
    try:
        cfg = MODEL_PRESETS[name]
    except KeyError:
        raise KeyError(
            f"unknown model config {name!r}; available: {sorted(MODEL_PRESETS)}"
        ) from None
    return replace(cfg, **overrides) if overrides else cfg
