from llm_consensus_tpu.models.config import MODEL_PRESETS, ModelConfig, get_config
from llm_consensus_tpu.models.transformer import forward, init_kv_cache, init_params

__all__ = [
    "MODEL_PRESETS",
    "ModelConfig",
    "forward",
    "get_config",
    "init_kv_cache",
    "init_params",
]
