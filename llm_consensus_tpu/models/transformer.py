"""Generic decoder-only transformer in functional JAX.

One implementation serves every model family (llama/mistral/gemma/qwen2/
mixtral) via static ``ModelConfig`` switches. This replaces the reference's
"compute layer" — three HTTP clients (/root/reference/internal/provider/
{openai,anthropic,google}.go) — with real on-device compute.

TPU-first design decisions:
  * Parameters are plain pytrees (nested dicts of arrays) with layers
    **stacked** on a leading axis; the layer loop is a ``lax.scan`` so XLA
    compiles one layer body regardless of depth (fast compiles, weight
    streaming during decode).
  * KV cache is a static-shaped [L, B, S_max, Hkv, dh] ring written with
    ``dynamic_update_slice`` — no shape changes between decode steps, so
    every step reuses the same compiled program.
  * All matmuls keep bf16 inputs with fp32 accumulation where it matters
    (softmax, norms, router, final logits).
  * Sharding is applied externally via ``parallel.sharding.param_axes``,
    which mirrors this module's pytree structure with logical axis names.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from llm_consensus_tpu.utils.jaxcompat import shard_map as _shard_map
from llm_consensus_tpu.models.config import ModelConfig
from llm_consensus_tpu.ops.attention import attention, make_attention_mask
from llm_consensus_tpu.ops.mlp import gated_mlp
from llm_consensus_tpu.ops.moe import moe_block
from llm_consensus_tpu.ops.quant import (
    is_quantized, kv_layer, kv_read, kv_write_rows, qeinsum)
from llm_consensus_tpu.ops.norms import rms_norm
from llm_consensus_tpu.ops.rope import apply_rope, rope_angles, rope_inv_freq


# -- parameter init ----------------------------------------------------------


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16,
                leaf_hook=None) -> dict:
    """Random-init parameter pytree (layers stacked on axis 0).

    ``leaf_hook(name, array) -> array`` transforms each weight AS it is
    created — ops/quant.init_params_quantized uses it to quantize
    leaf-by-leaf so peak HBM is the quantized tree plus ONE bf16 leaf,
    not the full bf16 tree (the difference between an 8B random init
    fitting one 16 GB chip and OOMing before quantization starts). The
    key sequence is independent of the hook, so hooked and post-hoc
    quantization produce identical values.
    """
    keys = iter(jax.random.split(key, 16))

    def normal(k, shape, std, name=""):
        # Jitted so XLA fuses normal→scale→astype into one kernel that
        # writes ``dtype`` directly: the eager form materializes the
        # float32 intermediate, and on an 8B model that is a 7.5 GB
        # transient PER STACKED LEAF — the difference between the
        # streamed-quantized init fitting one 16 GB chip or not.
        # Values are identical (same op chain, same key).
        w = jax.jit(
            lambda kk: (
                jax.random.normal(kk, shape, jnp.float32) * std
            ).astype(dtype)
        )(k)
        return leaf_hook(name, w) if leaf_hook is not None else w

    d, dh, hq, hkv, f, l = (
        cfg.d_model, cfg.head_dim, cfg.n_heads, cfg.n_kv_heads, cfg.d_ff, cfg.n_layers,
    )
    proj_std = d ** -0.5
    layers: dict = {
        "attn_norm": jnp.ones((l, d), dtype),
        "mlp_norm": jnp.ones((l, d), dtype),
        "wq": normal(next(keys), (l, d, hq * dh), proj_std, "wq"),
        "wk": normal(next(keys), (l, d, hkv * dh), proj_std, "wk"),
        "wv": normal(next(keys), (l, d, hkv * dh), proj_std, "wv"),
        "wo": normal(next(keys), (l, hq * dh, d), (hq * dh) ** -0.5, "wo"),
    }
    if cfg.norm_offset:
        # offset parameterization: stored weights are (w - offset), init 0
        layers["attn_norm"] = jnp.zeros((l, d), dtype)
        layers["mlp_norm"] = jnp.zeros((l, d), dtype)
    if cfg.qkv_bias:
        layers["bq"] = jnp.zeros((l, hq * dh), dtype)
        layers["bk"] = jnp.zeros((l, hkv * dh), dtype)
        layers["bv"] = jnp.zeros((l, hkv * dh), dtype)
    if cfg.is_moe:
        e = cfg.n_experts
        layers["w_router"] = normal(next(keys), (l, d, e), proj_std, "w_router")
        layers["w_gate"] = normal(next(keys), (l, e, d, f), proj_std, "w_gate")
        layers["w_up"] = normal(next(keys), (l, e, d, f), proj_std, "w_up")
        layers["w_down"] = normal(next(keys), (l, e, f, d), f ** -0.5, "w_down")
    else:
        layers["w_gate"] = normal(next(keys), (l, d, f), proj_std, "w_gate")
        layers["w_up"] = normal(next(keys), (l, d, f), proj_std, "w_up")
        layers["w_down"] = normal(next(keys), (l, f, d), f ** -0.5, "w_down")

    params = {
        "embed": normal(next(keys), (cfg.vocab_size, d), 0.02, "embed"),
        "final_norm": (jnp.zeros if cfg.norm_offset else jnp.ones)((d,), dtype),
        "layers": layers,
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(
            next(keys), (d, cfg.vocab_size), proj_std, "lm_head"
        )
    return params


def init_kv_cache(
    cfg: ModelConfig, batch: int, max_seq: Optional[int] = None,
    dtype=jnp.bfloat16, quant: Optional[str] = None,
) -> dict:
    """Static-shaped KV cache [L, B, S, Hkv, dh] (zeros, nothing valid yet).

    ``quant="int8"`` stores codes + per-row scales (ops/quant.py): half the
    HBM capacity and decode read bandwidth of a bf16 cache.
    """
    s = max_seq or cfg.max_seq_len
    shape = (cfg.n_layers, batch, s, cfg.n_kv_heads, cfg.head_dim)
    if quant == "int8":
        # Scales are stored seq-MINOR [L, B, Hkv, S]: with seq on lanes
        # the decode kernel's scale blocks tile exactly, where a
        # [..., Hkv, 1] layout pads its 1-wide lane dim to 128 in VMEM
        # (measured: the padded blocks alone blew the 16 MB scoped-VMEM
        # limit at batch 8).
        entry = lambda: {  # noqa: E731
            "q8": jnp.zeros(shape, jnp.int8),
            "s": jnp.zeros(
                (cfg.n_layers, batch, cfg.n_kv_heads, s), dtype
            ),
        }
        return {"k": entry(), "v": entry()}
    if quant is not None:
        raise ValueError(f"unknown kv cache quant mode {quant!r}")
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


# -- forward -----------------------------------------------------------------


def embed_tokens(params: dict, cfg: ModelConfig, tokens: jax.Array) -> jax.Array:
    """Token embedding lookup (+ Gemma's sqrt(d) scale) → [B, T, D]."""
    x = params["embed"][tokens].astype(params["embed"].dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), x.dtype)
    return x


def unembed(params: dict, cfg: ModelConfig, x: jax.Array) -> jax.Array:
    """Final norm + LM head (+ final logit softcap) → fp32 logits [B, T, V]."""
    x = rms_norm(x, params["final_norm"], cfg.rms_eps, cfg.norm_offset)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = qeinsum("btd,dv->btv", x, head, preferred_element_type=jnp.float32)
    if cfg.final_logit_softcap is not None:
        logits = cfg.final_logit_softcap * jnp.tanh(logits / cfg.final_logit_softcap)
    return logits


def _layer(
    cfg: ModelConfig,
    x: jax.Array,            # [B, T, D]
    lp: dict,                # this layer's params (leading L axis removed)
    cos: jax.Array,
    sin: jax.Array,
    mask: Optional[jax.Array],  # [B, T, S]; None on the flash paths
    cache_k: Optional[jax.Array],  # FULL K stack [L, B, S, Hkv, dh]
    cache_v: Optional[jax.Array],
    start_pos: Optional[jax.Array],
    layer_idx: Optional[jax.Array] = None,  # this layer's slot in the stack
    flash_offset: Optional[int] = None,  # static q_offset → use Pallas kernel
    flash_mesh=None,  # wrap the kernel in shard_map over this mesh's tp axis
    kv_width: Optional[int] = None,  # attend only cache[:, :kv_width]
    qkv_pin=None,  # mesh: pin q/k/v head shardings (non-dividing tp)
    ring_mesh=None,  # SP prefill: ring attention over this mesh's sp axis
    decode_flash: bool = False,  # T=1: fused Pallas decode-attention kernel
    row_start: Optional[jax.Array] = None,  # [B] (decode_flash path only)
    prefix_k=None,        # shared-prefix K stack [L, 1, P, Hkv, dh] (or int8 dict)
    prefix_v=None,
    prefix_len=None,      # scalar i32: valid prefix slots
    prefix_rows=None,     # [B] bool: rows that attend the shared prefix
) -> tuple[jax.Array, Optional[jax.Array], Optional[jax.Array]]:
    b, t, d = x.shape
    hq, hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    h = rms_norm(x, lp["attn_norm"], cfg.rms_eps, cfg.norm_offset)
    q = qeinsum("btd,dk->btk", h, lp["wq"])
    k = qeinsum("btd,dk->btk", h, lp["wk"])
    v = qeinsum("btd,dk->btk", h, lp["wv"])
    if cfg.qkv_bias:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(b, t, hq, dh)
    k = k.reshape(b, t, hkv, dh)
    v = v.reshape(b, t, hkv, dh)
    if qkv_pin is not None and ring_mesh is None:
        # Non-dividing tp: the projection output shards split WITHIN a
        # head (e.g. Hkv=2 over tp=4 → 16-wide shards of a 32-wide head),
        # and GSPMD carrying that layout through the rope/cache-write
        # scan miscompiles on jax 0.4.x (measured O(1) logit error, not
        # ulps — the seed test_sp_prefill non-dividing-tp failure). Pin
        # each tensor to its head-aligned sharding — replicated heads
        # when tp doesn't divide that head count — BEFORE rope and the
        # cache write, matching cache_specs' degraded layout. Dividing
        # meshes never reach here (qkv_pin stays None), so the working
        # sharded paths are untouched.
        from jax.sharding import NamedSharding, PartitionSpec as P

        tp_sz = dict(qkv_pin.shape)["tp"]

        def pin(t_, n_heads_):
            ax = "tp" if n_heads_ % tp_sz == 0 else None
            return jax.lax.with_sharding_constraint(
                t_, NamedSharding(qkv_pin, P(None, None, ax, None))
            )

        q, k, v = pin(q, hq), pin(k, hkv), pin(v, hkv)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    if cache_k is not None:
        # Write this step's keys/values at (layer_idx, start_pos) into the
        # FULL stacked cache (quantized on write for int8 caches), then
        # attend over this layer's entry — prefix-sliced to kv_width when
        # set, so attention cost scales with the caller's frontier bound,
        # not cache capacity. The full-stack in-place write (vs. threading
        # per-layer entries through the scan as xs/ys) is what lets XLA
        # alias the cache through both the layer scan and the decode-step
        # scan instead of copying it every step — see kv_write_rows.
        cache_k = kv_write_rows(cache_k, k, layer_idx, start_pos)
        cache_v = kv_write_rows(cache_v, v, layer_idx, start_pos)
        if decode_flash:
            # The decode kernel consumes the FULL code stacks directly
            # and pages its layer via the BlockSpec index map — no
            # per-layer slice, no relayout, no materialized dequant
            # (profiled at ~4-6 ms/step of pure copies at batch 32 in
            # the sliced form). int8 SCALE stacks are the exception:
            # the kernel slices them to the layer itself (1.6 MB) — the
            # full stacks got staged into the custom call's operand
            # space per call (decode_attention.py, round-5 profile).
            k_att, v_att = cache_k, cache_v
        elif flash_offset == 0 and (kv_width is None or kv_width >= t):
            # One-shot prefill from position 0 (the batched-admission and
            # first-chunk case): the causal frontier IS this chunk, so
            # attention needs exactly the k/v just computed — reading
            # them back out of the cache costs a per-layer dynamic-slice
            # copy plus (for int8 caches) a full-width dequant pass, all
            # for values we are still holding. int8 caches round-trip the
            # fresh tensors through quantize→dequantize so the attended
            # values stay BIT-IDENTICAL to a cache read-back (attention
            # quality loss applies uniformly across impls — greedy parity
            # with the XLA path depends on it).
            if is_quantized(cache_k):
                from llm_consensus_tpu.ops.quant import quantize_kv

                def roundtrip(fresh):
                    q8, sc = quantize_kv(fresh)
                    return q8.astype(x.dtype) * sc.astype(x.dtype)

                k_att, v_att = roundtrip(k), roundtrip(v)
            else:
                k_att, v_att = k.astype(x.dtype), v.astype(x.dtype)
        else:
            width = kv_width
            if flash_offset is not None:
                # The Pallas prefill kernel re-slices to the causal
                # frontier anyway, but slicing BEFORE kv_read keeps an
                # int8 cache's dequant bounded by the frontier too — the
                # kernel is a custom call, so XLA can't fuse the dequant
                # into it the way it does for the XLA attention path.
                frontier = flash_offset + t
                width = frontier if width is None else min(width, frontier)
            entry_k = kv_layer(cache_k, layer_idx, width)
            entry_v = kv_layer(cache_v, layer_idx, width)
            k_att = kv_read(entry_k, x.dtype)
            v_att = kv_read(entry_v, x.dtype)
    else:
        k_att, v_att = k, v

    if ring_mesh is not None:
        from llm_consensus_tpu.parallel.ring import ring_attention

        # Sequence-parallel prefill: q/k/v are sequence-sharded over sp
        # (the whole sequence never lands on one device); ring attention
        # circulates KV blocks over ICI. Heads stay tp-sharded when the
        # mesh has a tp axis — the ring and the head split compose
        # without communicating. This layer's k/v are returned (in place
        # of cache entries) so the caller can assemble the decode cache.
        # Heads ride the tp axis only when it divides both head counts —
        # the same gating as the flash path; otherwise heads replicate
        # over tp and only the ring shards work.
        tp_size = ring_mesh.shape.get("tp", 1)
        head_axis = (
            "tp" if tp_size > 1 and hq % tp_size == 0 and hkv % tp_size == 0
            else None
        )
        attn_out = ring_attention(
            q, k_att, v_att, ring_mesh,
            axis_name="sp",
            head_axis=head_axis,
            scale=dh ** -0.5,
            sliding_window=cfg.sliding_window,
            logit_softcap=cfg.attn_logit_softcap,
        )
    elif flash_offset is not None:
        from llm_consensus_tpu.ops.pallas import flash_attention

        fa = partial(
            flash_attention,
            q_offset=flash_offset,
            scale=dh ** -0.5,
            sliding_window=cfg.sliding_window,
            logit_softcap=cfg.attn_logit_softcap,
        )
        if flash_mesh is not None:
            # Per-head attention over TP-sharded heads: each shard runs the
            # kernel on its own q/kv head slice — no collectives inside.
            from jax.sharding import PartitionSpec as P

            spec = P(None, None, "tp", None)  # [B, S, H, dh], heads on tp
            fa = _shard_map(
                fa, mesh=flash_mesh,
                in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False,
            )
        attn_out = fa(q, k_att, v_att)
    elif decode_flash:
        from llm_consensus_tpu.ops.pallas import decode_attention

        with_state = prefix_k is not None
        da = partial(
            decode_attention,
            scale=dh ** -0.5,
            sliding_window=cfg.sliding_window,
            logit_softcap=cfg.attn_logit_softcap,
            kv_width=kv_width,
            return_state=with_state,
        )
        rs = row_start
        if rs is None:
            rs = jnp.zeros((b,), jnp.int32)
        if flash_mesh is not None:
            from jax.sharding import PartitionSpec as P

            spec = P(None, None, "tp", None)  # [B, 1, H, dh], heads on tp
            # Codes keep heads on axis 3 ([L, B, S, Hkv, dh]); the
            # seq-minor scale leaves are 4-D [L, B, Hkv, S] with heads
            # on axis 2 — each leaf gets the spec matching its rank.
            from llm_consensus_tpu.ops.quant import kv_seq_axis

            spec5 = P(None, None, None, "tp", None)
            spec4s = P(None, None, "tp", None)
            kv_spec = (
                jax.tree.map(
                    lambda leaf: spec5 if kv_seq_axis(leaf) == 2 else spec4s,
                    k_att,
                )
                if is_quantized(k_att) else spec5
            )
            da = _shard_map(
                da, mesh=flash_mesh,
                in_specs=(spec, kv_spec, kv_spec, P(), P(), P(None)),
                out_specs=(spec, P(None, "tp"), P(None, "tp"))
                if with_state else spec,
                check_vma=False,
            )
        attn_out = da(
            q, k_att, v_att, jnp.asarray(start_pos, jnp.int32), layer_idx, rs
        )
        if with_state:
            attn_out, m2, l2 = attn_out
            m2, l2 = m2[:, None], l2[:, None]  # [B, Hq] → [B, T=1, Hq]
    else:
        attn_out = attention(
            q, k_att, v_att, mask,
            scale=dh ** -0.5,
            logit_softcap=cfg.attn_logit_softcap,
            return_state=prefix_k is not None,
        )
        if prefix_k is not None:
            attn_out, m2, l2 = attn_out

    if prefix_k is not None:
        # Shared-prefix merge (the pool's one-prompt fan-out pattern):
        # every participating row attends ONE replicated prefix KV —
        # read once per step as a dense MXU matmul — instead of carrying
        # its own copy of the prompt KV through the per-row cache sweep.
        # Exact: two-source online-softmax combine of (prefix, own-row)
        # attention. Rows not flagged in ``prefix_rows`` contribute
        # (m=−inf, l=0) and pass through unchanged.
        from llm_consensus_tpu.ops.attention import (
            merge_attention_states, prefix_attention)

        pk = kv_read(kv_layer(prefix_k, layer_idx), x.dtype)[0]  # [P, Hkv, dh]
        pv = kv_read(kv_layer(prefix_v, layer_idx), x.dtype)[0]
        o1, m1, l1 = prefix_attention(
            q, pk, pv, prefix_len, prefix_rows,
            scale=dh ** -0.5,
            logit_softcap=cfg.attn_logit_softcap,
        )
        attn_out = merge_attention_states(o1, m1, l1, attn_out, m2, l2)
    x = x + qeinsum("btk,kd->btd", attn_out.reshape(b, t, hq * dh), lp["wo"])

    h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps, cfg.norm_offset)
    if cfg.is_moe:
        mlp_out = moe_block(
            h, lp["w_router"], lp["w_gate"], lp["w_up"], lp["w_down"],
            top_k=cfg.experts_per_token, activation=cfg.activation,
        )
    else:
        mlp_out = gated_mlp(h, lp["w_gate"], lp["w_up"], lp["w_down"], cfg.activation)
    if ring_mesh is not None:
        return x + mlp_out, k, v  # fresh k/v for the caller's cache build
    return x + mlp_out, cache_k, cache_v


def forward(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,                 # [B, T] int32
    cache: Optional[dict] = None,      # init_kv_cache(...) or None
    start_pos: jax.Array | int = 0,    # first absolute position of `tokens`
    remat: bool = False,               # rematerialize each layer (training)
    attn_impl: str = "xla",            # "xla" | "flash" (Pallas prefill kernel)
    mesh=None,                         # engine's mesh when params are TP-sharded
    kv_width: Optional[int] = None,    # attend only cache[:, :kv_width] (static)
    logits_index: Optional[jax.Array] = None,  # [B]: unembed only this position
    row_start: Optional[jax.Array] = None,  # [B]: first real slot per row
    prefix: Optional[dict] = None,     # shared-prefix KV cache [L, 1, P, Hkv, dh]
    prefix_len: Optional[jax.Array] = None,  # scalar i32 valid prefix slots
    prefix_rows: Optional[jax.Array] = None,  # [B] bool: rows attending prefix
    kv_mask: Optional[jax.Array] = None,  # [B, S] bool: written-slot bitmap
) -> tuple[jax.Array, Optional[dict]]:
    """Run the model. Returns (logits [B, T, V] fp32, updated cache).

    Without a cache this is a plain training/eval forward over ``tokens``.
    With a cache it serves both prefill (T = prompt chunk) and decode (T = 1):
    keys/values are written at ``start_pos`` and attention spans the whole
    cache with invalid slots masked.

    ``remat=True`` checkpoints each scanned layer so the backward pass
    recomputes activations instead of keeping them live across all layers —
    the standard HBM-for-FLOPs trade on TPU (activations, not weights, are
    what blow past HBM at training sequence lengths).

    ``attn_impl="flash"`` routes cache prefill (T > 1, static ``start_pos``)
    through the fused Pallas kernel (ops/pallas/flash_attention.py), which
    never materializes the [B, Hq, T, S] score tensor and bounds work by
    the causal frontier instead of cache capacity. Shapes the kernel can't
    tile (or decode steps) silently fall back to the XLA path, so "flash"
    is always safe to request.

    ``mesh``: when the params/cache carry TP NamedShardings, the Pallas
    kernel (a Mosaic custom call with no GSPMD partitioning rule) is wrapped
    in ``shard_map`` over the ``tp`` axis — per-head attention is
    embarrassingly parallel over the sharded head dim, so each shard runs
    the kernel on its own heads with no collectives. Gated to tp-only
    meshes whose degree divides both head counts; anything else falls back
    to the XLA path, which GSPMD partitions natively.
    """
    if attn_impl == "ring":
        if cache is None or mesh is None or not (
            isinstance(start_pos, int) and start_pos == 0
        ):
            raise ValueError(
                "attn_impl='ring' is a one-shot sequence-parallel prefill: "
                "it needs a cache, a mesh with an sp axis, and start_pos=0"
            )
        return _forward_ring_prefill(
            params, cfg, tokens, cache, mesh, logits_index
        )

    if row_start is not None and cache is None:
        raise ValueError(
            "row_start (left-padded batching) requires a cache: the "
            "no-cache mask path has no kv_valid to exclude pad slots"
        )
    if prefix is not None:
        if cache is None:
            raise ValueError("a shared prefix requires a cache")
        if cfg.sliding_window is not None:
            # Windowed attention would need the window to span the
            # prefix/suffix seam; the pool gates the feature off instead.
            raise ValueError("shared-prefix attention does not compose "
                             "with sliding_window")
        if prefix_len is None:
            raise ValueError("prefix requires prefix_len")
    if kv_mask is not None:
        # Written-slot bitmap (batched speculative decode): per-row
        # acceptance leaves REJECTED slots behind the shared frontier
        # holding junk KV that is never rewritten, so slot validity is no
        # longer the contiguous [row_start, frontier) interval — the
        # bitmap is the complete per-(row, slot) validity source and the
        # row_start clamp is skipped below. Positions of old valid slots
        # computed from the CURRENT row_start underestimate their true
        # write-time positions (row_start only grows as holes accrue),
        # which keeps the causal compare correct for full attention —
        # every valid old slot is strictly in the past of every query —
        # but NOT for sliding windows, hence the gate.
        if cache is None or row_start is None:
            raise ValueError("kv_mask requires a cache and row_start")
        if cfg.sliding_window is not None:
            raise ValueError("kv_mask (speculative holes) does not "
                             "compose with sliding_window")

    b, t = tokens.shape
    x = embed_tokens(params, cfg, tokens)

    from llm_consensus_tpu.ops.pallas.flash_attention import flash_supported

    # shard_tp: 1 = unsharded (run the kernel bare), >1 = tp-only mesh (run
    # it under shard_map), 0 = mesh has a non-trivial non-tp axis — the
    # kernel would see sharded operands it can't partition, so force XLA.
    shard_tp = 1
    if mesh is not None:
        sizes = dict(mesh.shape)
        tp = sizes.pop("tp", 1)
        shard_tp = tp if all(v == 1 for v in sizes.values()) else 0
    if shard_tp == 0:
        flash_heads_ok = False
    elif shard_tp == 1:
        flash_heads_ok = flash_supported(t, cfg.n_heads, cfg.n_kv_heads)
    else:
        flash_heads_ok = (
            cfg.n_heads % shard_tp == 0
            and cfg.n_kv_heads % shard_tp == 0
            and flash_supported(
                t, cfg.n_heads // shard_tp, cfg.n_kv_heads // shard_tp
            )
        )
    flash_offset = (
        int(start_pos)
        if (
            attn_impl == "flash"
            and cache is not None
            and isinstance(start_pos, int)
            and row_start is None  # kernel assumes one shared offset
            and prefix is None     # prefill kernel has no merge-state form
            and kv_mask is None    # kernels derive validity from pos alone
            and flash_heads_ok
        )
        else None
    )
    # T=1 decode steps (traced start_pos) take the fused decode kernel:
    # the XLA route's mask build + tiny batched matmuls + softmax cost a
    # chain of kernel launches per layer per step.
    from llm_consensus_tpu.ops.pallas.decode_attention import (
        decode_flash_supported)

    if cache is not None:
        k_store = cache["k"]["q8"] if is_quantized(cache["k"]) else cache["k"]
        decode_width = k_store.shape[2] if kv_width is None else min(
            kv_width, k_store.shape[2]
        )
        decode_quantized = is_quantized(cache["k"])
    else:
        decode_width, decode_quantized = None, False
    if shard_tp == 1:
        decode_heads_ok = decode_flash_supported(
            cfg.n_heads, cfg.n_kv_heads, cfg.head_dim,
            width=decode_width, quantized=decode_quantized,
        )
    elif shard_tp > 1:
        decode_heads_ok = (
            cfg.n_heads % shard_tp == 0
            and cfg.n_kv_heads % shard_tp == 0
            and decode_flash_supported(
                cfg.n_heads // shard_tp, cfg.n_kv_heads // shard_tp,
                cfg.head_dim, width=decode_width,
                quantized=decode_quantized,
            )
        )
    else:
        decode_heads_ok = False
    decode_flash = (
        attn_impl == "flash"
        and cache is not None
        and t == 1
        and flash_offset is None
        and kv_mask is None  # the decode kernel has no bitmap form
        and decode_heads_ok
    )
    flash_mesh = mesh if (
        (flash_offset is not None or decode_flash) and shard_tp > 1
    ) else None

    start = jnp.asarray(start_pos, jnp.int32)
    positions = start + jnp.arange(t, dtype=jnp.int32)[None, :]  # [1, T]
    if row_start is not None:
        # Right-aligned batch (left-padded rows): positions are
        # row-relative so every row's first real token is position 0 —
        # RoPE, causality, and sliding windows all follow.
        positions = positions - row_start[:, None]
    positions = jnp.broadcast_to(positions, (b, t))
    pos_offset = None
    if prefix is not None:
        # Suffix-resident rows: cache slot j holds ABSOLUTE position
        # prefix_len + (j − row_start) for participating rows, so RoPE
        # angles (and the mask's causal compare below) shift by the
        # prefix length. Non-participating rows carry their full prompt
        # in their own window — no shift.
        plen = jnp.asarray(prefix_len, jnp.int32)
        if prefix_rows is not None:
            pos_offset = plen * prefix_rows.astype(jnp.int32)  # [B]
        else:
            pos_offset = jnp.broadcast_to(plen, (b,))
        positions = positions + pos_offset[:, None]
    inv_freq = rope_inv_freq(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling_dict)
    cos, sin = rope_angles(positions, inv_freq)

    if flash_offset is not None or decode_flash:
        mask = None  # the kernels derive causality from pos/q_offset
    elif cache is not None:
        k_store = cache["k"]["q8"] if is_quantized(cache["k"]) else cache["k"]
        s = k_store.shape[2]
        if kv_width is not None:
            s = min(s, kv_width)
        kv_slots = jnp.arange(s, dtype=jnp.int32)[None, :]
        kv_valid = jnp.broadcast_to(kv_slots < (start + t), (b, s))
        if kv_mask is not None:
            # Bitmap validity (speculative holes): slots the bitmap
            # clears are junk even below the frontier, and valid slots
            # may sit below row_start (which accrues hole counts, not
            # the row's first slot) — the bitmap replaces the interval
            # clamp entirely. Slots at/above the frontier inside this
            # call's write window are marked valid by the CALLER before
            # dispatch (intra-window causality comes from the position
            # compare below).
            kv_positions = jnp.broadcast_to(kv_slots, (b, s)) - row_start[:, None]
            kv_valid = jnp.logical_and(kv_valid, kv_mask[:, :s])
        elif row_start is not None:
            kv_positions = jnp.broadcast_to(kv_slots, (b, s)) - row_start[:, None]
            kv_valid = jnp.logical_and(kv_valid, kv_slots >= row_start[:, None])
        else:
            kv_positions = jnp.broadcast_to(kv_slots, (b, s))
        if pos_offset is not None:
            # Keep the causal compare in the same (absolute) basis the
            # query positions moved to.
            kv_positions = kv_positions + pos_offset[:, None]
        mask = make_attention_mask(positions, kv_positions, kv_valid, cfg.sliding_window)
    else:
        mask = make_attention_mask(positions, positions, None, cfg.sliding_window)

    qkv_pin = None
    if mesh is not None and cache is not None:
        tp_sz = dict(mesh.shape).get("tp", 1)
        if tp_sz > 1 and (cfg.n_heads % tp_sz or cfg.n_kv_heads % tp_sz):
            qkv_pin = mesh
    layer_fn = partial(
        _layer, cfg, flash_offset=flash_offset, flash_mesh=flash_mesh,
        kv_width=kv_width, qkv_pin=qkv_pin,
        decode_flash=decode_flash, row_start=row_start,
        prefix_k=prefix["k"] if prefix is not None else None,
        prefix_v=prefix["v"] if prefix is not None else None,
        prefix_len=prefix_len,
        prefix_rows=prefix_rows,
    )

    if cache is not None:
        # The cache rides the scan CARRY (full stacks, in-place row
        # writes), not xs/ys: the xs→ys form makes XLA materialize a
        # fresh copy of both stacks every outer decode step.
        def scan_body(carry, lp):
            x, ck, cv, li = carry
            x, ck, cv = layer_fn(x, lp, cos, sin, mask, ck, cv, start,
                                 layer_idx=li)
            return (x, ck, cv, li + 1), None

        (x, new_k, new_v, _), _ = jax.lax.scan(
            scan_body,
            (x, cache["k"], cache["v"], jnp.asarray(0, jnp.int32)),
            params["layers"],
        )
        new_cache = {"k": new_k, "v": new_v}
    else:
        def scan_body(x, lp):
            x, _, _ = layer_fn(x, lp, cos, sin, mask, None, None, None)
            return x, None

        if remat:
            scan_body = jax.checkpoint(scan_body)
        x, _ = jax.lax.scan(scan_body, x, params["layers"])
        new_cache = None

    if logits_index is not None:
        # Prefill only samples one position; unembedding every position
        # would spend T×V×D FLOPs on logits nobody reads (~30% of an 8B
        # prefill at a 128k vocab).
        x = jnp.take_along_axis(x, logits_index[:, None, None], axis=1)
    return unembed(params, cfg, x), new_cache


def _forward_ring_prefill(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,     # [B, T], T divisible by the mesh's sp size
    cache: dict,           # init_kv_cache(...); T ≤ its capacity
    mesh,                  # Mesh with an "sp" axis (tp optional)
    logits_index: Optional[jax.Array],
) -> tuple[jax.Array, dict]:
    """Sequence-parallel one-shot prefill (SURVEY §5 long-context path).

    Activations are sharded over ``sp`` on the sequence dim, so no device
    ever materializes the whole prompt's activations; attention is ring
    attention (parallel/ring.py) with KV blocks circulating over ICI, and
    heads stay tp-sharded when the mesh has both axes. Per-layer K/V come
    back from the scan and are written into the decode cache in one
    update — GSPMD inserts the sp all-gather there, the single point
    where the full sequence assembles (the cache itself is the decode
    requirement). The judge's concatenated panel prompt is the consumer:
    its prefill footprint per chip drops by the sp factor.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from llm_consensus_tpu.ops.quant import quantize_kv

    b, t = tokens.shape
    x = embed_tokens(params, cfg, tokens)
    x = jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(None, "sp", None))
    )
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None, :], (b, t))
    inv_freq = rope_inv_freq(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling_dict)
    cos, sin = rope_angles(positions, inv_freq)
    layer_fn = partial(_layer, cfg, ring_mesh=mesh)

    def scan_body(x, lp):
        x, k, v = layer_fn(x, lp, cos, sin, None, None, None, None)
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(scan_body, x, params["layers"])

    def write(entry, stack):  # [L, B, T, Hkv, dh] → cache positions [0, T)
        if is_quantized(entry):
            q8, s = quantize_kv(stack)
            s_rows = jnp.swapaxes(s[..., 0], 2, 3)  # [L, B, Hkv, T]
            return {
                "q8": jax.lax.dynamic_update_slice(
                    entry["q8"], q8, (0, 0, 0, 0, 0)
                ),
                "s": jax.lax.dynamic_update_slice(
                    entry["s"], s_rows.astype(entry["s"].dtype), (0, 0, 0, 0)
                ),
            }
        return jax.lax.dynamic_update_slice(
            entry, stack.astype(entry.dtype), (0, 0, 0, 0, 0)
        )

    new_cache = {"k": write(cache["k"], ks), "v": write(cache["v"], vs)}
    if logits_index is not None:
        x = jnp.take_along_axis(x, logits_index[:, None, None], axis=1)
    return unembed(params, cfg, x), new_cache
