"""Data flywheel: served corpus → judge distillation → live hot-swap.

The serving stack journals every consensus run into ``data/<run-id>/``
(manifest, panel answers, judge verdict). This package closes the loop
the ROADMAP names:

  * :mod:`~llm_consensus_tpu.flywheel.corpus` — scan the run dirs
    (``run.json`` manifests are the sole authority), extract
    (panel-answers → judge-verdict) pairs into a deduplicated, versioned
    training set with a deterministic train/holdout split;
  * :mod:`~llm_consensus_tpu.flywheel.distill` — pjit data-parallel
    distillation of the journaled judge onto a student model
    (soft-target KL from the teacher's logits + hard-label CE on the
    verdict tokens), optimizer state sharded along ``dp``, orbax
    checkpoints tagged with a monotone weight-version id + corpus hash;
  * :mod:`~llm_consensus_tpu.flywheel.canary` — the rollout half:
    version-labeled live metrics compared between baseline and canary
    replicas, with automatic rollback on regression.

The hot-swap half lives where the weights live — ``Engine.swap_weights``
(engine/engine.py) and the batcher's pin discipline (engine/batcher.py);
this package orchestrates it from the outside.
"""

from llm_consensus_tpu.flywheel.canary import CanaryWatcher  # noqa: F401
from llm_consensus_tpu.flywheel.corpus import (  # noqa: F401
    Corpus, Example, build_corpus, scan_run_dirs,
)
