"""Flywheel corpus: (panel-answers → judge-verdict) pairs from data/.

``data/`` holds one dir per run — but not ONLY runs: the observability
stack parks auxiliary artifacts beside them (``blackbox/`` flight-
recorder dumps, ``roofline-*/`` profiles, ``elastic-r*/`` replica state;
new writers use ``data/_artifacts/``). The scanner therefore trusts
exactly one signal: a ``run.json`` manifest (written by both the CLI and
the serve scheduler before execution). No manifest → not a run → skipped,
whatever the dir looks like.

Each valid run contributes one training example: the rendered judge
prompt (the SAME template serving uses — consensus/judge.py
``render_judge_prompt``, so the student learns the distribution it will
be queried on) paired with the journaled verdict text. Examples dedup by
content hash (re-served prompts, cache-miss retries), split
deterministically into train/holdout by hash — stable across rescans, so
holdout examples never leak into train as the corpus grows — and the
whole set is identified by a corpus hash that checkpoint metadata carries
(flywheel/distill.py): a weight version names exactly the data it saw.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Optional

from llm_consensus_tpu.utils import knobs

# Reserved namespace for non-run artifacts under data/ (profiles, dumps,
# replica state). The manifest rule already skips them; the constant
# exists so writers and scanner agree on one name.
ARTIFACTS_DIRNAME = "_artifacts"


@dataclass
class Example:
    """One distillation pair: judge prompt in, judge verdict out."""

    run_id: str
    prompt: str  # rendered judge prompt (teacher/student input)
    verdict: str  # journaled consensus text (hard-label target)
    key: str = ""  # content hash — dedup + split identity

    def __post_init__(self) -> None:
        if not self.key:
            h = hashlib.sha256()
            h.update(self.prompt.encode("utf-8"))
            h.update(b"\x00")
            h.update(self.verdict.encode("utf-8"))
            self.key = h.hexdigest()


@dataclass
class Corpus:
    """A versioned, deduplicated training set extracted from data/."""

    corpus_hash: str
    train: list = field(default_factory=list)
    holdout: list = field(default_factory=list)
    runs_scanned: int = 0  # dirs with a run.json manifest
    runs_skipped: int = 0  # dirs without one (artifacts, foreign)
    runs_corrupt: int = 0  # manifested runs whose payload didn't parse
    deduped: int = 0  # duplicate pairs dropped
    # Booked exclusions (integrity plane): the run ids whose pairs were
    # refused — torn JSON, digest mismatches, injected corruption — so
    # an operator can audit exactly which data the student never saw.
    corrupt_ids: list = field(default_factory=list)

    @property
    def version(self) -> str:
        """Short corpus identity for checkpoint tags and logs."""
        return self.corpus_hash[:12]

    def summary(self) -> dict:
        return {
            "corpus_hash": self.corpus_hash,
            "version": self.version,
            "train": len(self.train),
            "holdout": len(self.holdout),
            "runs_scanned": self.runs_scanned,
            "runs_skipped": self.runs_skipped,
            "runs_corrupt": self.runs_corrupt,
            "corrupt_ids": list(self.corrupt_ids),
            "deduped": self.deduped,
        }


def scan_run_dirs(data_dir: str) -> "tuple[list, int]":
    """``([(run_id, run_dir)], skipped)`` — manifest-validated run dirs.

    ``run.json`` is the sole authority: a dir without one (or with one
    that isn't a JSON object) is skipped and counted, never guessed at
    by name shape. Sorted by run id so the corpus is order-stable.
    """
    runs: list = []
    skipped = 0
    try:
        entries = sorted(os.listdir(data_dir))
    except OSError:
        return [], 0
    for name in entries:
        run_dir = os.path.join(data_dir, name)
        if not os.path.isdir(run_dir):
            continue
        manifest_path = os.path.join(run_dir, "run.json")
        try:
            with open(manifest_path, "r", encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            skipped += 1
            continue
        if not isinstance(manifest, dict):
            skipped += 1
            continue
        runs.append((name, run_dir))
    return runs, skipped


def pair_digest(doc: dict) -> str:
    """Canonical content digest over the fields a distillation pair
    consumes (prompt, consensus verdict, panel response texts) — what
    the serve scheduler stamps into ``result.json`` as
    ``integrity_digest`` and :func:`_extract` re-derives before a pair
    may enter the corpus."""
    from llm_consensus_tpu import integrity

    return integrity.canonical_digest({
        "prompt": doc.get("prompt"),
        "consensus": doc.get("consensus"),
        "responses": [
            r.get("content") if isinstance(r, dict) else None
            for r in (doc.get("responses") or [])
        ],
    })


def _extract(run_id: str, run_dir: str) -> Optional[Example]:
    """One run's distillation pair, or None when the payload is unusable
    (no result.json yet — crashed/in-flight run — empty verdict, or a
    single-response run the judge never actually synthesized)."""
    path = os.path.join(run_dir, "result.json")
    if not os.path.exists(path):
        return None  # in-flight or crashed run: manifest only, no result
    try:
        with open(path, "r", encoding="utf-8") as f:
            result = json.load(f)
    except (OSError, ValueError):
        raise CorruptRun(run_id)
    if not isinstance(result, dict):
        raise CorruptRun(run_id)
    from llm_consensus_tpu import integrity

    plane = integrity.plane()
    want = result.get("integrity_digest")
    if plane is not None and isinstance(want, str):
        # A stamped pair must reproduce its digest: a run dir whose
        # bytes rotted after the stamp (or were tampered with) is a
        # poisoned training example — book it, never distill it.
        plane.check("corpus")
        if pair_digest(result) != want:
            plane.failure(
                "corpus", f"pair digest mismatch in run {run_id}"
            )
            raise CorruptRun(run_id)
    verdict = result.get("consensus")
    responses = result.get("responses")
    if not verdict or not isinstance(responses, list) or len(responses) < 2:
        # One response is returned verbatim (judge.go:74-79 parity) —
        # there is no judge behavior to distill from it.
        return None
    from llm_consensus_tpu.consensus.judge import render_judge_prompt
    from llm_consensus_tpu.providers.base import Response

    panel = []
    for r in responses:
        if not isinstance(r, dict) or not r.get("content"):
            return None
        panel.append(Response(
            model=str(r.get("model", "")),
            content=str(r["content"]),
            provider=str(r.get("provider", "")),
        ))
    prompt = render_judge_prompt(str(result.get("prompt", "")), panel)
    return Example(run_id=run_id, prompt=prompt, verdict=str(verdict))


class CorruptRun(ValueError):
    """A manifested run whose result.json does not parse."""


def build_corpus(
    data_dir: Optional[str] = None,
    holdout: Optional[float] = None,
) -> Corpus:
    """Scan ``data_dir``, extract, dedup, and split the corpus.

    Deterministic end to end: dirs scan sorted, dedup keeps the first
    occurrence, and the split hashes each example's content key — an
    example lands on the same side of the split however many runs
    surround it. Corrupt runs (torn result.json, injected
    ``corpus_corrupt``) are counted and skipped, never fatal: a corpus
    build must survive the journal of a crashed serving process.
    """
    if data_dir is None:
        data_dir = knobs.get_str("LLMC_DATA_DIR")
    if holdout is None:
        holdout = float(knobs.get_float("LLMC_DISTILL_HOLDOUT"))
    holdout = min(max(holdout, 0.0), 1.0)
    from llm_consensus_tpu import faults

    plan = faults.plan()
    runs, skipped = scan_run_dirs(data_dir)
    corpus = Corpus(corpus_hash="", runs_skipped=skipped)
    seen: set = set()
    examples: list = []
    for run_id, run_dir in runs:
        corpus.runs_scanned += 1
        if plan is not None:
            hit = plan.fire("swap", phase="corpus", run=run_id)
            if hit is not None and hit.kind == "corpus_corrupt":
                corpus.runs_corrupt += 1
                corpus.corrupt_ids.append(run_id)
                continue
        try:
            ex = _extract(run_id, run_dir)
        except CorruptRun:
            corpus.runs_corrupt += 1
            corpus.corrupt_ids.append(run_id)
            continue
        if ex is None:
            continue
        if ex.key in seen:
            corpus.deduped += 1
            continue
        seen.add(ex.key)
        examples.append(ex)
    h = hashlib.sha256()
    for ex in examples:
        h.update(ex.key.encode("ascii"))
    corpus.corpus_hash = h.hexdigest()
    for ex in examples:
        # Split on a DIFFERENT hash than the dedup key's raw prefix so
        # the fraction is uniform even if key prefixes ever correlate
        # with content shape.
        frac = int(hashlib.sha256(
            ex.key.encode("ascii") + b"/split"
        ).hexdigest()[:8], 16) / float(16 ** 8)
        (corpus.holdout if frac < holdout else corpus.train).append(ex)
    return corpus


def encode_examples(tokenizer, examples: list, seq: int) -> dict:
    """Token batch for the distill step: ``{tokens, targets, mask}``.

    Per example: ``BOS + prompt_ids + verdict_ids``, next-token shifted,
    truncated/padded to ``seq``. The loss mask covers ONLY positions
    whose *target* is a verdict token — the student is graded on judging,
    not on parroting the panel prompt — and padding is dead. Long prompts
    truncate from the LEFT (keep the verdict and the panel tail nearest
    it); examples whose verdict is entirely cut are dropped by mask.

    Returns plain nested lists (callers wrap in jnp) so this stays
    importable without jax for corpus-only tooling.
    """
    tokens, targets, mask = [], [], []
    for ex in examples:
        prompt_ids = tokenizer.encode(ex.prompt, add_bos=True)
        verdict_ids = tokenizer.encode(ex.verdict, add_bos=False)
        ids = prompt_ids + verdict_ids
        is_verdict = [0] * len(prompt_ids) + [1] * len(verdict_ids)
        if len(ids) > seq + 1:
            ids = ids[-(seq + 1):]
            is_verdict = is_verdict[-(seq + 1):]
        row_t = ids[:-1]
        row_y = ids[1:]
        row_m = is_verdict[1:]
        pad = seq - len(row_t)
        if pad > 0:
            row_t = row_t + [0] * pad
            row_y = row_y + [0] * pad
            row_m = row_m + [0] * pad
        tokens.append(row_t)
        targets.append(row_y)
        mask.append([float(m) for m in row_m])
    return {"tokens": tokens, "targets": targets, "mask": mask}


__all__ = [
    "ARTIFACTS_DIRNAME", "Corpus", "CorruptRun", "Example",
    "build_corpus", "encode_examples", "pair_digest", "scan_run_dirs",
]
