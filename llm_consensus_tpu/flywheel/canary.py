"""Canary watcher: auto-rollback for freshly swapped weights.

The last guard of the data flywheel. After a distilled checkpoint is
hot-swapped in (Engine.swap_weights via POST /v1/swap), the router's
canary lane steers an ``LLMC_CANARY_FRACTION`` slice of the keyspace at
the new version while everyone else stays on baseline (serve/router.py).
The :class:`CanaryWatcher` compares the two cohorts' latency tails and
pulls the cord when the new weights regress serving — rolling back is
one call (Engine.rollback_weights restores the double-buffered previous
params under a NEW monotone version), so the cost of a bad checkpoint is
a few windows of slightly slow canary traffic, never an incident.

The watcher is deliberately transport-agnostic: feed it version-labeled
request latencies with :meth:`record` from wherever canary traffic is
visible — the router's proxy loop (replica weight version), a gateway
serving a swapped engine (its own ``weight_version()``), or a dryrun
lane's probe clients. :meth:`tick` closes one comparison window, in the
:class:`~llm_consensus_tpu.obs.live.SLOWatcher` idiom: a regression must
hold for ``LLMC_CANARY_WINDOWS`` CONSECUTIVE windows before ``on_regress``
fires (one slow window is noise, N in a row is the new weights), each
window needs ``LLMC_CANARY_MIN_SAMPLES`` in BOTH cohorts to count
(starved cohorts reset the streak — no verdicts from anecdotes), and
firing re-arms the streak so the next regression needs N fresh windows.
"""

from __future__ import annotations

from typing import Callable, Optional

from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.utils import knobs

# Per-(version, window) sample cap: the watcher compares tails, it does
# not archive traffic — beyond this, extra samples change p99 by noise.
_WINDOW_CAP = 4096


def _quantile(sorted_values: list, q: float) -> float:
    """Nearest-rank quantile of an already-sorted, non-empty list."""
    idx = min(len(sorted_values) - 1, int(q * len(sorted_values)))
    return sorted_values[idx]


class CanaryWatcher:
    """p99-ratio streak over version-labeled latencies ⇒ rollback hook.

    ``on_regress`` receives one dict (canary/baseline versions, p99s,
    ratio, streak length) and is expected to roll the canary back —
    e.g. ``lambda info: provider.rollback_weights(model)`` or a POST to
    the gateway's ``/v1/swap`` with ``action: rollback``. Exceptions
    from the hook are swallowed: a broken rollback path must not take
    the serving thread that ticked the watcher down with it.
    """

    def __init__(
        self,
        tol: Optional[float] = None,
        windows: Optional[int] = None,
        min_samples: Optional[int] = None,
        on_regress: Optional[Callable[[dict], None]] = None,
    ):
        self.tol = (
            knobs.get_float("LLMC_CANARY_LATENCY_TOL") if tol is None else tol
        )
        self.windows = max(1, (
            knobs.get_int("LLMC_CANARY_WINDOWS") if windows is None
            else windows
        ))
        self.min_samples = max(1, (
            knobs.get_int("LLMC_CANARY_MIN_SAMPLES") if min_samples is None
            else min_samples
        ))
        self.on_regress = on_regress
        self._lock = sanitizer.make_lock("flywheel.canary")
        self._window: dict = {}  # version -> [latency_s, ...] (open window)
        self._streak = 0
        self.windows_closed = 0
        self.regressions = 0
        self.last_ratio: Optional[float] = None

    # -- feeding --------------------------------------------------------------

    def record(self, version: int, latency_s: float) -> None:
        """One request latency served at ``version`` (0 = baseline)."""
        with self._lock:
            bucket = self._window.setdefault(int(version), [])
            if len(bucket) < _WINDOW_CAP:
                bucket.append(float(latency_s))

    # -- evaluation -----------------------------------------------------------

    def tick(self) -> bool:
        """Close the open window and judge it; True when a rollback
        fired. Call on a fixed cadence (the live plane's rotation hook,
        a lane's probe loop) — window length IS the caller's cadence."""
        with self._lock:
            window, self._window = self._window, {}
            self.windows_closed += 1
            versions = sorted(window)
            if len(versions) < 2:
                # Version-uniform traffic: nothing to compare. NOT a
                # streak reset — a lull in canary placement must not
                # erase evidence already accumulated against it.
                return False
            baseline, canary = versions[0], versions[-1]
            base_samples = sorted(window[baseline])
            canary_samples = sorted(window[canary])
            if (
                len(base_samples) < self.min_samples
                or len(canary_samples) < self.min_samples
            ):
                self._streak = 0  # starved window: anecdotes don't count
                return False
            base_p99 = _quantile(base_samples, 0.99)
            canary_p99 = _quantile(canary_samples, 0.99)
            ratio = canary_p99 / max(base_p99, 1e-9)
            self.last_ratio = round(ratio, 4)
            if ratio <= self.tol:
                self._streak = 0
                return False
            self._streak += 1
            if self._streak < self.windows:
                return False
            self._streak = 0  # re-arm: the NEXT verdict needs N windows
            self.regressions += 1
            info = {
                "canary_version": canary,
                "baseline_version": baseline,
                "canary_p99_s": canary_p99,
                "baseline_p99_s": base_p99,
                "ratio": self.last_ratio,
                "windows": self.windows,
            }
            hook = self.on_regress
        # Outside the lock: the hook rolls weights back (engine swap
        # lock) — holding the watcher lock across it would stack a
        # foreign lock under flywheel.canary for no reason.
        if hook is not None:
            try:
                hook(info)
            except Exception:  # noqa: BLE001 — rollback hook must not kill us
                pass
        return True

    def stats(self) -> dict:
        with self._lock:
            return {
                "windows_closed": self.windows_closed,
                "regressions": self.regressions,
                "streak": self._streak,
                "last_ratio": self.last_ratio,
                "tol": self.tol,
                "windows": self.windows,
                "min_samples": self.min_samples,
            }
