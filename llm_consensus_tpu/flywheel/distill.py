"""pjit data-parallel judge distillation over the served corpus.

Revives train/step.py into the flywheel's training half: the student
model trains on ``alpha * KL(teacher logits) + (1-alpha) * CE(verdict
tokens)`` (train/loss.py ``distill_loss``) over examples extracted from
``data/`` run dirs (flywheel/corpus.py). TPU-first shape carried over
from the train step:

  * one jitted function per step — student forward, teacher forward,
    backward, optimizer — with the previous state donated so params +
    moments update in place in HBM;
  * parallelism declared, not coded: params on ``param_specs``, the
    batch constrained to ``P('dp', 'sp')``, and optimizer moments on
    ``opt_moment_specs`` — the cross-replica-sharding scheme that
    partitions AdamW state over ``dp`` instead of mirroring it;
  * the teacher is frozen reference compute inside the same program
    (its logits go through ``stop_gradient``), so XLA schedules both
    forwards against the same collectives.

Checkpoints are Orbax (engine/checkpoint.py) under a **versioned**
layout the hot-swap half consumes::

    <out_dir>/v<NNNN>/params/   # orbax param tree
    <out_dir>/v<NNNN>/version.json
        {"version": N, "corpus_hash": ..., "student": ..., "step": ...}

``version`` is monotone per out_dir (``next_version`` scans), and the
corpus hash names exactly the data the weights saw — an
``Engine.swap_weights(version, params)`` call is traceable back to its
training set by construction.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_consensus_tpu.models import forward, init_params
from llm_consensus_tpu.models.config import ModelConfig, get_config
from llm_consensus_tpu.parallel.sharding import (
    opt_moment_specs, param_specs, shard_pytree,
)
from llm_consensus_tpu.train.loss import distill_loss
from llm_consensus_tpu.train.step import TrainState, _batch_spec
from llm_consensus_tpu.utils import knobs


def default_distill_optimizer(
    lr: Optional[float] = None, weight_decay: float = 0.1,
    clip_norm: float = 1.0,
) -> optax.GradientTransformation:
    """AdamW + global-norm clip at the distillation learning rate."""
    if lr is None:
        lr = float(knobs.get_float("LLMC_DISTILL_LR"))
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def opt_state_shardings(
    optimizer: optax.GradientTransformation,
    params: dict,
    cfg: ModelConfig,
    mesh: Mesh,
):
    """NamedSharding pytree for ``optimizer.init(params)``'s output.

    Walks the abstract optimizer state by path: any leaf under an ``mu``
    or ``nu`` attribute is a param-tree mirror and takes that param's
    :func:`opt_moment_specs` placement; everything else (step counts,
    empty states) replicates. Path-based so it holds for any optax chain
    that nests Adam-style moments, without depending on the chain's
    tuple layout.
    """
    mspecs = opt_moment_specs(cfg, mesh)
    moment_by_path = {
        tuple(path): spec
        for path, spec in jax.tree_util.tree_flatten_with_path(mspecs)[0]
    }
    abstract = jax.eval_shape(optimizer.init, params)
    leaves, treedef = jax.tree_util.tree_flatten_with_path(abstract)
    out = []
    for path, _leaf in leaves:
        spec = P()
        for i, entry in enumerate(path):
            if getattr(entry, "name", None) in ("mu", "nu"):
                spec = moment_by_path.get(tuple(path[i + 1:]), P())
                break
        out.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, out)


def init_distill_state(
    cfg: ModelConfig,
    key: jax.Array,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    dtype=jnp.bfloat16,
    params: Optional[dict] = None,
) -> TrainState:
    """Init (or adopt) student params + cross-replica-sharded moments.

    Like train/step.py ``init_train_state``, but ``optimizer.init`` runs
    with explicit ``out_shardings`` from :func:`opt_state_shardings`, so
    the AdamW mu/nu buffers are born dp-partitioned instead of
    mirroring their params' placement.
    """
    if params is None:
        params = init_params(cfg, key, dtype=dtype)
    if mesh is not None:
        params = shard_pytree(params, param_specs(cfg, mesh), mesh)
        opt_state = jax.jit(
            optimizer.init,
            out_shardings=opt_state_shardings(optimizer, params, cfg, mesh),
        )(params)
    else:
        opt_state = jax.jit(optimizer.init)(params)
    return TrainState(
        step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state
    )


def make_distill_step(
    cfg: ModelConfig,
    teacher_cfg: ModelConfig,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    remat: bool = True,
    temperature: float = 2.0,
    alpha: float = 0.5,
):
    """Jitted ``step_fn(state, teacher_params, batch) -> (state, metrics)``.

    ``batch`` is ``{"tokens", "targets", "mask"}`` each [B, T]; metrics
    carries scalar fp32 ``loss`` / ``kl`` / ``ce`` / ``grad_norm``. The
    teacher forward runs inside the same program, un-differentiated
    (``distill_loss`` stop-gradients its logits).
    """
    spec = _batch_spec(mesh)

    def step_fn(state: TrainState, teacher_params: dict, batch: dict):
        if mesh is not None:
            batch = {
                k: jax.lax.with_sharding_constraint(
                    v, NamedSharding(mesh, spec)
                )
                for k, v in batch.items()
            }
        teacher_logits, _ = forward(
            teacher_params, teacher_cfg, batch["tokens"], remat=remat
        )

        def loss_fn(params):
            logits, _ = forward(params, cfg, batch["tokens"], remat=remat)
            return distill_loss(
                logits, teacher_logits, batch["targets"], batch.get("mask"),
                temperature=temperature, alpha=alpha,
            )

        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss, "kl": aux["kl"], "ce": aux["ce"],
            "grad_norm": optax.global_norm(grads),
        }
        return (
            TrainState(
                step=state.step + 1, params=params, opt_state=opt_state
            ),
            metrics,
        )

    return jax.jit(step_fn, donate_argnums=0)


def make_distill_eval(
    cfg: ModelConfig,
    teacher_cfg: ModelConfig,
    mesh: Optional[Mesh] = None,
    remat: bool = True,
    temperature: float = 2.0,
    alpha: float = 0.5,
):
    """Jitted ``eval_fn(params, teacher_params, batch) -> loss`` for the
    holdout split — same objective, no gradient, nothing donated."""
    spec = _batch_spec(mesh)

    def eval_fn(params: dict, teacher_params: dict, batch: dict):
        if mesh is not None:
            batch = {
                k: jax.lax.with_sharding_constraint(
                    v, NamedSharding(mesh, spec)
                )
                for k, v in batch.items()
            }
        teacher_logits, _ = forward(
            teacher_params, teacher_cfg, batch["tokens"], remat=remat
        )
        logits, _ = forward(params, cfg, batch["tokens"], remat=remat)
        loss, _aux = distill_loss(
            logits, teacher_logits, batch["targets"], batch.get("mask"),
            temperature=temperature, alpha=alpha,
        )
        return loss

    return jax.jit(eval_fn)


# -- versioned checkpoints ---------------------------------------------------


def _version_dirs(out_dir: str) -> "list[tuple[int, str]]":
    """``[(version, dir)]`` ascending for every ``v<NNNN>/`` in out_dir."""
    out = []
    try:
        entries = os.listdir(out_dir)
    except OSError:
        return []
    for name in entries:
        if name.startswith("v") and name[1:].isdigit():
            out.append((int(name[1:]), os.path.join(out_dir, name)))
    out.sort()
    return out


def next_version(out_dir: str) -> int:
    """The next monotone weight-version id for ``out_dir`` (serving
    starts at version 0, so the first distilled checkpoint is 1)."""
    dirs = _version_dirs(out_dir)
    return (dirs[-1][0] + 1) if dirs else 1


def latest_checkpoint(out_dir: str) -> Optional[dict]:
    """``{"version", "params_path", ...version.json fields}`` of the
    newest checkpoint under ``out_dir``, or None."""
    for version, vdir in reversed(_version_dirs(out_dir)):
        meta_path = os.path.join(vdir, "version.json")
        params_path = os.path.join(vdir, "params")
        if not os.path.isdir(params_path):
            continue
        try:
            with open(meta_path, "r", encoding="utf-8") as f:
                meta = json.load(f)
        except (OSError, ValueError):
            meta = {}
        meta.setdefault("version", version)
        meta["params_path"] = params_path
        return meta
    return None


def save_checkpoint(
    out_dir: str, version: int, params: dict, meta: dict
) -> str:
    """Write ``<out_dir>/v<NNNN>/{params/, version.json}``; returns the
    version dir. version.json lands LAST so a torn save (crash mid-orbax
    write) is never picked up by :func:`latest_checkpoint` — and lands
    durably: tmp-write + ``os.replace`` + fsync of the file AND its
    directory, so a power cut after this returns cannot leave a version
    whose metadata evaporates. The ``params_digest`` stamped here is the
    param-tree content digest :meth:`Engine.swap_weights` re-derives
    before installing a buffer (integrity plane) — a checkpoint whose
    bytes rotted between save and swap is refused, never served."""
    from llm_consensus_tpu import integrity
    from llm_consensus_tpu.engine.checkpoint import save_params

    vdir = os.path.join(out_dir, f"v{version:04d}")
    os.makedirs(vdir, exist_ok=True)
    save_params(params, os.path.join(vdir, "params"))
    doc = dict(meta)
    doc["version"] = version
    doc["params_digest"] = integrity.digest_tree(params)
    meta_path = os.path.join(vdir, "version.json")
    tmp_path = meta_path + ".tmp"
    with open(tmp_path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp_path, meta_path)
    _fsync_dir(vdir)
    return vdir


def _fsync_dir(path: str) -> None:
    """fsync a directory: ``os.replace`` makes the rename atomic, but
    only a directory fsync makes it DURABLE — without it a power cut can
    roll the directory entry back to a file that no longer exists."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory-open semantics
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# -- the loop ----------------------------------------------------------------


def _batches(encoded: dict, batch: int, seq: int, steps: int):
    """Cycle the encoded corpus into ``steps`` [batch, seq] jnp batches.

    Examples repeat round-robin when the corpus is smaller than
    ``steps * batch`` — CI corpora are a handful of runs; the loop's
    contract is "≥1 step reduces holdout loss", not epoch accounting.
    """
    n = len(encoded["tokens"])
    if n == 0:
        return
    idx = 0
    for _ in range(steps):
        rows = [(idx + i) % n for i in range(batch)]
        idx = (idx + batch) % n
        yield {
            "tokens": jnp.asarray(
                [encoded["tokens"][r] for r in rows], jnp.int32
            ),
            "targets": jnp.asarray(
                [encoded["targets"][r] for r in rows], jnp.int32
            ),
            "mask": jnp.asarray(
                [encoded["mask"][r] for r in rows], jnp.float32
            ),
        }


def run_distill(
    corpus,
    student: str = "tiny-llama",
    teacher: Optional[str] = None,
    out_dir: Optional[str] = None,
    *,
    mesh: Optional[Mesh] = None,
    checkpoint_dir: Optional[str] = None,
    steps: Optional[int] = None,
    lr: Optional[float] = None,
    batch: Optional[int] = None,
    seq: Optional[int] = None,
    temperature: Optional[float] = None,
    alpha: Optional[float] = None,
    dtype=jnp.float32,
    log=None,
) -> dict:
    """One distillation run over ``corpus``; returns its summary dict.

    Loads student/teacher weights from ``checkpoint_dir/<preset>/`` when
    present (the serving checkpoints — the teacher IS the journaled
    judge), else random-inits with distinct seeds so the KL target is
    non-degenerate on CI tiny models. Evaluates the holdout split before
    and after training — ``holdout_loss_after < holdout_loss_before`` is
    the flywheel lane's acceptance signal — and saves one versioned
    checkpoint (plus every ``LLMC_DISTILL_CKPT_EVERY`` steps) tagged with
    the corpus hash.
    """
    teacher = teacher or student
    steps = steps if steps is not None else int(
        knobs.get_int("LLMC_DISTILL_STEPS"))
    batch = batch if batch is not None else int(
        knobs.get_int("LLMC_DISTILL_BATCH"))
    seq = seq if seq is not None else int(knobs.get_int("LLMC_DISTILL_SEQ"))
    temperature = temperature if temperature is not None else float(
        knobs.get_float("LLMC_DISTILL_TEMP"))
    alpha = alpha if alpha is not None else float(
        knobs.get_float("LLMC_DISTILL_ALPHA"))
    ckpt_every = int(knobs.get_int("LLMC_DISTILL_CKPT_EVERY"))
    if log is None:
        log = lambda _msg: None  # noqa: E731

    cfg = get_config(student)
    teacher_cfg = get_config(teacher)
    tokenizer = None
    student_params = None
    teacher_params = None
    if checkpoint_dir:
        from llm_consensus_tpu.engine.checkpoint import try_load_params
        from llm_consensus_tpu.engine.tokenizer import load_tokenizer

        student_params = try_load_params(
            cfg, os.path.join(checkpoint_dir, student), mesh=mesh)
        teacher_params = try_load_params(
            teacher_cfg, os.path.join(checkpoint_dir, teacher), mesh=mesh)
        tokenizer = load_tokenizer(os.path.join(checkpoint_dir, student))
    if tokenizer is None:
        from llm_consensus_tpu.engine.tokenizer import ByteTokenizer

        tokenizer = ByteTokenizer()
    if teacher_params is None:
        teacher_params = init_params(
            teacher_cfg, jax.random.PRNGKey(1), dtype=dtype)
        if mesh is not None:
            teacher_params = shard_pytree(
                teacher_params, param_specs(teacher_cfg, mesh), mesh)

    from llm_consensus_tpu.flywheel.corpus import encode_examples

    encoded = encode_examples(tokenizer, corpus.train, seq)
    holdout = encode_examples(
        tokenizer, corpus.holdout or corpus.train, seq)
    summary = dict(corpus.summary())
    summary.update({
        "student": student, "teacher": teacher, "steps": 0,
        "batch": batch, "seq": seq,
    })
    if not encoded["tokens"]:
        summary["error"] = "empty corpus"
        return summary

    optimizer = default_distill_optimizer(lr)
    state = init_distill_state(
        cfg, jax.random.PRNGKey(0), optimizer, mesh=mesh, dtype=dtype,
        params=student_params,
    )
    step_fn = make_distill_step(
        cfg, teacher_cfg, optimizer, mesh=mesh,
        temperature=temperature, alpha=alpha,
    )
    eval_fn = make_distill_eval(
        cfg, teacher_cfg, mesh=mesh,
        temperature=temperature, alpha=alpha,
    )

    def holdout_loss(params) -> float:
        total, n = 0.0, 0
        for b in _batches(
            holdout, batch, seq,
            max(1, (len(holdout["tokens"]) + batch - 1) // batch),
        ):
            total += float(eval_fn(params, teacher_params, b))
            n += 1
        return total / max(n, 1)

    from llm_consensus_tpu.obs import attrib

    summary["holdout_loss_before"] = holdout_loss(state.params)
    version = next_version(out_dir) if out_dir else 0
    last_metrics: dict = {}
    done = 0
    for i, b in enumerate(_batches(encoded, batch, seq, steps)):
        with attrib.tag("train_step"):
            state, metrics = step_fn(state, teacher_params, b)
        last_metrics = {k: float(v) for k, v in metrics.items()}
        done = i + 1
        log(f"distill step {done}/{steps}: "
            f"loss={last_metrics['loss']:.4f} "
            f"kl={last_metrics['kl']:.4f} ce={last_metrics['ce']:.4f}")
        if out_dir and ckpt_every and done % ckpt_every == 0 and done < steps:
            save_checkpoint(out_dir, version, state.params, {
                "corpus_hash": corpus.corpus_hash, "student": student,
                "teacher": teacher, "step": done, **last_metrics,
            })
            version += 1
    summary["steps"] = done
    summary.update(last_metrics)
    summary["holdout_loss_after"] = holdout_loss(state.params)
    if out_dir:
        vdir = save_checkpoint(out_dir, version, state.params, {
            "corpus_hash": corpus.corpus_hash, "student": student,
            "teacher": teacher, "step": done,
            "holdout_loss_before": summary["holdout_loss_before"],
            "holdout_loss_after": summary["holdout_loss_after"],
            **last_metrics,
        })
        summary["weight_version"] = version
        summary["checkpoint"] = vdir
    return summary


__all__ = [
    "default_distill_optimizer", "init_distill_state", "latest_checkpoint",
    "make_distill_eval", "make_distill_step", "next_version",
    "opt_state_shardings", "run_distill", "save_checkpoint",
]
