"""End-to-end integrity plane: corruption detection, containment, repair.

Every robustness layer below this one (journal/replay, failover,
migration, hot-swap) assumes the bytes it moves are correct. At pod
scale silent data corruption — a defective chip, a torn disk write, a
flipped bit on a cross-host wire — is a *when*, not an *if*, and a
single bad byte in a KV block, a WAL record, or a swapped checkpoint
otherwise flows straight to a client as garbage or poisons a distilled
corpus. This package is the uniform detect → contain → repair contract
over every byte-crossing seam:

  * **Detect** — CRC32C framing on every ``StreamJournal`` WAL record
    (recovery/journal.py), content digests on KV pool blocks computed at
    ``publish`` and verified on the host-visible paths (handoff
    cross-mesh transfer, migration resume state, sampled radix gathers —
    kv/pool.py, engine/handoff.py, serve/elastic.py), param-tree digests
    recorded in the flywheel ``version.json`` and verified before
    ``swap_weights`` installs a buffer (flywheel/distill.py,
    engine/engine.py), and a fused finite-logit sentinel on the batched
    decode fetch path (engine/engine.py ``_decode_chunk`` — one
    ``jnp.isfinite`` reduce piggybacked on the existing fetch).
  * **Contain** — a poisoned row fails only its stream with a typed
    :class:`IntegrityError` SSE terminal (never garbage bytes to a
    client); repeated fires on one replica walk the ``quarantined``
    lifecycle state (serve/elastic.py) — the router stops placing,
    residents migrate away, ``/healthz`` reports it; a digest-mismatched
    checkpoint is refused with 409; corrupt corpus pairs are booked and
    excluded from distillation (flywheel/corpus.py).
  * **Repair** — WAL torn tails truncate to the last good record and
    feed the normal replay contract; a failed KV gather verification
    drops the radix node and recomputes the prefill (reuse lost, never
    correctness); quarantine is reversible via consecutive clean probe
    windows.

The plane is opt-in (``LLMC_INTEGRITY=1``): consumers bind it once at
construction (``self._integrity = integrity.plane()``) so disabled runs
pay a single ``is not None`` check — and a clean run with the plane on
stays byte-identical to plane-off.
"""

from __future__ import annotations

from llm_consensus_tpu.integrity.core import (  # noqa: F401 — public API
    CHECKSUM_LEN,
    IntegrityCounters,
    IntegrityError,
    IntegrityPlane,
    canonical_digest,
    counters,
    crc32c,
    crc32_str,
    digest_array,
    digest_bytes,
    digest_tree,
    frame_wal_line,
    install,
    parse_wal_line,
    plane,
    QuarantineTracker,
    reset,
)

__all__ = [
    "CHECKSUM_LEN", "IntegrityCounters", "IntegrityError", "IntegrityPlane",
    "QuarantineTracker", "canonical_digest", "counters", "crc32c",
    "crc32_str", "digest_array", "digest_bytes", "digest_tree",
    "frame_wal_line",
    "install", "parse_wal_line", "plane", "reset",
]
