"""Integrity primitives: checksums, digests, counters, the plane.

Two checksum tiers, chosen by what they protect:

  * :func:`crc32c` — CRC32C (Castagnoli), table-driven pure Python. Used
    to frame ``StreamJournal`` WAL records: the records are short lines,
    the polynomial is the storage-industry standard for exactly this
    torn-write case, and the pure-Python cost on a <100-byte line is
    noise next to the ``write()`` beside it.
  * :func:`digest_bytes` — ``zlib.crc32`` (C speed) for bulk content:
    KV blocks, param trees, migration payloads. These run over megabytes
    on host-visible copies; a C-speed rolling checksum keeps the plane
    inside its ≤2% overhead budget without new dependencies.

Digests are hex strings (stable across processes, JSON-safe) so they can
ride ``version.json``, migration records, and per-slot tables verbatim.

The plane itself follows the faults/obs singleton pattern: ``plane()``
resolves ``LLMC_INTEGRITY`` exactly once and caches the result (None
when off). Consumers bind it at construction
(``self._integrity = integrity.plane()``) so disabled runs pay a single
``is not None`` check on the hot paths. ``install()`` / ``reset()``
exist for tests and the integrity dryrun lane.
"""

from __future__ import annotations

import json
import zlib
from typing import Optional

from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.utils import knobs

# -- CRC32C (Castagnoli) ------------------------------------------------------

_CRC32C_POLY = 0x82F63B78


def _crc32c_table() -> tuple:
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
        table.append(c)
    return tuple(table)


_CRC_TABLE = _crc32c_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32C (Castagnoli) of ``data``, optionally continuing ``crc``."""
    c = crc ^ 0xFFFFFFFF
    for b in data:
        c = _CRC_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


# -- WAL record framing (recovery/journal.py) ---------------------------------

# Every framed WAL line is ``<crc32c-8-hex> <payload>``: fixed-width
# checksum first so the torn-tail scan needs no payload parse to decide
# whether a record survived the write.
CHECKSUM_LEN = 8


def frame_wal_line(payload: str) -> str:
    """One WAL record framed for the disk mirror (no trailing newline)."""
    return f"{crc32c(payload.encode('utf-8')):0{CHECKSUM_LEN}x} {payload}"


def parse_wal_line(line: str) -> Optional[str]:
    """The payload of one framed WAL line, or None when the frame is
    torn or corrupt (short line, bad hex, checksum mismatch)."""
    if len(line) < CHECKSUM_LEN + 2 or line[CHECKSUM_LEN] != " ":
        return None
    try:
        want = int(line[:CHECKSUM_LEN], 16)
    except ValueError:
        return None
    payload = line[CHECKSUM_LEN + 1:]
    if crc32c(payload.encode("utf-8")) != want:
        return None
    return payload


# -- bulk content digests -----------------------------------------------------


def digest_bytes(data: bytes, seed: int = 0) -> str:
    """C-speed rolling digest of ``data`` as 8 hex chars."""
    return f"{zlib.crc32(data, seed) & 0xFFFFFFFF:08x}"


def crc32_str(s: str, crc: int = 0) -> int:
    """Roll ``s`` into a running ``zlib.crc32`` — combining per-leaf
    digests into one chain/block digest without concatenating buffers."""
    return zlib.crc32(s.encode("utf-8"), crc) & 0xFFFFFFFF


def digest_array(arr) -> str:
    """Digest of one array's dtype, shape, AND content — a bit flip, a
    reshape, and a dtype cast all change it."""
    import numpy as np

    a = np.ascontiguousarray(np.asarray(arr))
    seed = zlib.crc32(f"{a.dtype.str}:{a.shape}".encode("utf-8"))
    return digest_bytes(a.tobytes(), seed)


def digest_tree(tree) -> str:
    """Digest of a param pytree: structure plus every leaf's content, in
    deterministic leaf order — what ``version.json`` records at save and
    ``swap_weights`` verifies before installing a buffer."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    acc = zlib.crc32(str(treedef).encode("utf-8"))
    for leaf in leaves:
        acc = zlib.crc32(digest_array(leaf).encode("utf-8"), acc)
    return f"{acc & 0xFFFFFFFF:08x}"


def canonical_digest(doc: dict) -> str:
    """Digest of a JSON document under canonical encoding (sorted keys,
    no whitespace) — stable across hosts and dict orderings; migration
    records carry this across the wire."""
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return digest_bytes(blob.encode("utf-8"))


# -- the typed failure --------------------------------------------------------


class IntegrityError(RuntimeError):
    """A corruption was detected and contained. ``surface`` names the
    seam (``wal`` / ``kv`` / ``handoff`` / ``migration`` / ``ckpt`` /
    ``decode``); the gateway maps this onto a typed SSE terminal so the
    client sees a classified failure, never the corrupt bytes."""

    def __init__(self, surface: str, detail: str):
        super().__init__(f"integrity failure at {surface}: {detail}")
        self.surface = surface
        self.detail = detail


# -- counters -----------------------------------------------------------------


class IntegrityCounters:
    """Per-surface check/failure counters, mirrored into the obs
    recorder (``integrity.*`` in metrics.json) and exported as the
    ``llmc_integrity_checks_total{surface}`` /
    ``llmc_integrity_failures_total{surface}`` families."""

    def __init__(self):
        from llm_consensus_tpu import obs

        self._lock = sanitizer.make_lock("integrity.counters")
        self._checks: dict = {}    # guarded by: _lock
        self._failures: dict = {}  # guarded by: _lock
        self._obs = obs.recorder()

    def check(self, surface: str, n: int = 1) -> None:
        with self._lock:
            self._checks[surface] = self._checks.get(surface, 0) + n
        if self._obs is not None:
            self._obs.count(f"integrity.checks.{surface}", n)

    def failure(self, surface: str, detail: str = "") -> None:
        with self._lock:
            self._failures[surface] = self._failures.get(surface, 0) + 1
        if self._obs is not None:
            self._obs.count(f"integrity.failures.{surface}")
            self._obs.instant(
                "integrity_failure", tid="integrity",
                surface=surface, detail=detail,
            )

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "checks": dict(self._checks),
                "failures": dict(self._failures),
                "checks_total": sum(self._checks.values()),
                "failures_total": sum(self._failures.values()),
            }

    def prom_families(self) -> dict:
        """The labeled counter families for /metricsz (obs/prom.py
        ``render(families=...)`` shape)."""
        with self._lock:
            checks = dict(self._checks)
            failures = dict(self._failures)
        return {
            "integrity_checks_total": {
                "type": "counter",
                "samples": [
                    ({"surface": s}, n) for s, n in sorted(checks.items())
                ],
            },
            "integrity_failures_total": {
                "type": "counter",
                "samples": [
                    ({"surface": s}, n) for s, n in sorted(failures.items())
                ],
            },
        }


# -- quarantine hysteresis ----------------------------------------------------


class QuarantineTracker:
    """The enter/probe/exit hysteresis for one replica, mirroring the
    fleet's suspect→healthy pattern: ``strike()`` returns True when the
    accumulated integrity failures cross the quarantine threshold;
    while quarantined, ``clean_probe()`` returns True after N
    *consecutive* clean probe windows (any new strike resets the run).
    """

    def __init__(self, threshold: int, probe_n: int):
        self._lock = sanitizer.make_lock("integrity.quarantine")
        self.threshold = max(1, threshold)
        self.probe_n = max(1, probe_n)
        self._strikes = 0        # guarded by: _lock
        self._clean_probes = 0   # guarded by: _lock
        self._quarantines = 0    # guarded by: _lock

    def strike(self) -> bool:
        """Record one integrity failure; True when quarantine should
        engage (exactly once per crossing — further strikes while
        already over threshold keep returning False until reset)."""
        with self._lock:
            self._strikes += 1
            self._clean_probes = 0
            if self._strikes == self.threshold:
                self._quarantines += 1
                return True
            return False

    def clean_probe(self) -> bool:
        """Record one clean probe window; True when the replica has
        earned its way back (``probe_n`` consecutive clean windows)."""
        with self._lock:
            self._clean_probes += 1
            if self._clean_probes >= self.probe_n:
                self._strikes = 0
                self._clean_probes = 0
                return True
            return False

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "strikes": self._strikes,
                "clean_probes": self._clean_probes,
                "quarantines": self._quarantines,
                "threshold": self.threshold,
                "probe_n": self.probe_n,
            }


# -- the plane ----------------------------------------------------------------


class IntegrityPlane:
    """Process-wide integrity plane: counters + the sampling policy.

    Sampling (radix-gather verification) is deterministic — every Nth
    sampled call where N derives from ``LLMC_INTEGRITY_SAMPLE`` — so two
    identical runs verify identical gathers and byte-identity contracts
    hold under the plane."""

    def __init__(self, sample: Optional[float] = None):
        if sample is None:
            sample = knobs.get_float("LLMC_INTEGRITY_SAMPLE")
        self.sample = max(0.0, min(1.0, sample))
        self._sample_every = round(1.0 / self.sample) if self.sample else 0
        self._lock = sanitizer.make_lock("integrity.plane")
        self._sample_clock = 0  # guarded by: _lock
        self.counters = IntegrityCounters()

    def sample_hit(self) -> bool:
        """True when this sampled-verification site should verify now."""
        if not self._sample_every:
            return False
        with self._lock:
            self._sample_clock += 1
            if self._sample_clock >= self._sample_every:
                self._sample_clock = 0
                return True
            return False

    def check(self, surface: str, n: int = 1) -> None:
        self.counters.check(surface, n)

    def failure(self, surface: str, detail: str = "") -> None:
        self.counters.failure(surface, detail)

    def stats(self) -> dict:
        out = self.counters.snapshot()
        out["sample"] = self.sample
        return out


_lock = sanitizer.make_lock("integrity.registry")
_plane: Optional[IntegrityPlane] = None
_resolved = False


def plane() -> Optional[IntegrityPlane]:
    """The process-wide integrity plane, or None when disabled."""
    global _plane, _resolved
    if not _resolved:
        with _lock:
            if not _resolved:
                if knobs.get_bool("LLMC_INTEGRITY"):
                    _plane = IntegrityPlane()
                _resolved = True
    return _plane


def counters() -> Optional[IntegrityCounters]:
    """The plane's counters, or None when the plane is off."""
    p = plane()
    return p.counters if p is not None else None


def install(p: Optional[IntegrityPlane]) -> None:
    """Install ``p`` as the process plane (tests / integrity dryrun)."""
    global _plane, _resolved
    with _lock:
        _plane = p
        _resolved = True


def reset() -> None:
    """Forget the cached plane; the next ``plane()`` re-reads the env."""
    global _plane, _resolved
    with _lock:
        _plane = None
        _resolved = False
