"""Cross-request paged KV pool with radix prefix sharing.

Serving millions of users means massive prompt overlap — shared system
prompts, the judge header, coalesced-cache near-misses that differ only
in the tail — yet the classic engine keeps ONE prompt snapshot per
engine (`engine._prefix_cache`): the second distinct prefix evicts the
first, and nothing is shared across requests that interleave.

This package generalizes that single slot into a cross-request cache
layer:

  * :mod:`kv.pool` — a block-granular paged KV pool: fixed-size token
    blocks over ONE preallocated per-leaf arena (an ``init_kv_cache``
    tree of capacity ``n_blocks × block_size``, sharded through the
    engine's own ``shard_fn`` so tp meshes shard it transparently),
    refcounted leases, copy-on-write on divergence, LRU eviction of
    unreferenced blocks.
  * :mod:`kv.radix` — a token-id radix trie mapping prompt prefixes to
    block chains, shared across streams, concurrent requests, and
    consensus rounds.

Wiring: behind ``LLMC_KV_POOL=1`` the pool REPLACES the engine's
single-slot snapshot — ``Engine._reusable_prefix`` becomes a radix
match + block gather and ``Engine._retain_prefix`` becomes a block
publish — so every existing reuse path (single-stream prefix restore,
admission-wave fork, the batcher's shared-prefix establishment) rides
the radix with no further changes, and with the flag off the classic
paths are byte-for-byte untouched.

Byte-identity invariant: blocks hold EXACT cache bytes (scatter and
gather are pure seq-axis copies of the same leaf layout, int8 codes and
scales included), always at absolute positions [0, n) of a left-aligned
[1, S] cache — so a gathered prefix is bit-identical to the snapshot
restore the classic path would have performed, and greedy decode is
byte-identical pool-on vs pool-off (asserted in tests/test_kv.py and
the ``kvpool`` dryrun lane).
"""

from __future__ import annotations


from llm_consensus_tpu.kv.pool import KVPool
from llm_consensus_tpu.kv.radix import RadixIndex
from llm_consensus_tpu.utils import knobs

__all__ = ["KVPool", "RadixIndex", "pool_enabled", "pool_for"]


def pool_enabled() -> bool:
    """The ONE LLMC_KV_POOL predicate — shared by :func:`pool_for` and
    everything that reports config (the gateway's ``llmc_build_info``
    feature labels), so the skew gauge can never disagree with what the
    engines actually did."""
    return knobs.get_bool("LLMC_KV_POOL")


def pool_for(engine) -> "KVPool | None":
    """The engine's cross-request KV pool, or None when disabled.

    Resolved at engine construction like the engine's other knobs
    (``LLMC_KV_POOL=1`` opts in; default off keeps the classic
    single-slot snapshot paths byte-identical). Chunked prefill is the
    gather's suffix program — ``prefill_chunk == 0`` (the documented
    chunking off-switch) disables the pool exactly as it disables the
    classic prefix reuse.
    """
    if not pool_enabled():
        return None
    if not engine.prefill_chunk or not engine.prefix_cache_enabled:
        return None
    return KVPool.for_engine(engine)
