"""Paged KV block pool: one preallocated arena + gather/scatter programs.

The device half of the cross-request KV cache (see the package
docstring). Layout decisions, TPU-first:

  * **One arena per engine**, allocated ONCE at pool construction as an
    ``init_kv_cache(batch=1, max_seq=n_blocks × block_size)`` tree and
    passed through the engine's own ``shard_fn`` — so every leaf keeps
    exactly the per-leaf NamedShardings a working cache has (int8 code
    stacks + seq-minor scale stacks included) and tp engines shard the
    pool transparently (GSPMD partitions the copy programs natively;
    the seq axis blocks live on is never sharded).
  * **One compiled program** (`_copy_blocks`) serves both directions:
    gather (arena → fresh [1, S] cache, the radix-hit fast path) and
    publish (finished cache → arena, donated so the write is in place).
    Block starts are TRACED operands and the block count pow2-buckets
    (padding repeats the last pair — an idempotent self-copy), so the
    compile set is logarithmic in chain length and shared across every
    distinct match.
  * **Bytes, not recompute**: blocks store exact cache bytes at absolute
    positions [0, n) of a left-aligned [1, S] cache — a gather costs
    seq-axis copy bandwidth where the prefill it replaces costs a full
    forward pass, and the gathered prefix is bit-identical to what the
    classic snapshot restore would have produced (the greedy
    byte-identity contract, asserted in tests/test_kv.py).

Concurrency: one pool lock serializes radix walks, slot accounting, and
device DISPATCH (enqueue only — execution overlaps on the device
stream). Host dispatch order is publish-after-gather whenever a slot is
recycled (eviction requires ``refs == 0``, and leases are held across
the gather dispatch), so in-order device streams make slot reuse safe
without any device-side synchronization.
"""

from __future__ import annotations

import time
import warnings
from functools import partial

import jax
import jax.numpy as jnp

from llm_consensus_tpu import integrity
from llm_consensus_tpu.obs.attrib import tag as attrib_tag
from llm_consensus_tpu.obs import roofline as _roofline
from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.utils import knobs


@partial(jax.jit, static_argnames=("k", "bs"), donate_argnames=("dst",))
def _copy_blocks(dst, src, src_starts, dst_starts, k: int, bs: int):
    """Copy ``k`` block-sized seq spans from ``src``'s leaves into
    ``dst``'s (both init_kv_cache trees; traced span starts, so ONE
    program per (k, bs) and leaf shapes). Gather and publish are the
    same program with the roles swapped; padding pairs repeat a real
    pair, which is an idempotent self-overwrite."""
    from llm_consensus_tpu.ops.quant import kv_seq_axis

    def leaf(d, s):
        ax = kv_seq_axis(d)
        for i in range(k):
            blk = jax.lax.dynamic_slice_in_dim(s, src_starts[i], bs, axis=ax)
            d = jax.lax.dynamic_update_slice_in_dim(
                d, blk, dst_starts[i], axis=ax
            )
        return d

    return jax.tree.map(leaf, dst, src)


# Roofline instrumentation (obs/roofline.py): gather and publish are one
# program with the roles swapped, so the ambient attribution tag at the
# dispatch site ("kv_gather" / "kv_publish") picks the family; the
# unrolled k-bucket copy is fully counted (no loop-body discount). The
# copied tokens (k x bs) feed the cross-check denominators.
_copy_blocks = _roofline.instrument(
    _copy_blocks, family="kv_gather",
    key=lambda a, k: (
        k.get("k", a[4] if len(a) > 4 else None),
        k.get("bs", a[5] if len(a) > 5 else None),
    ),
    tokens=lambda a, k: (
        int(k.get("k", a[4])) * int(k.get("bs", a[5]))
    ),
)


def _kbucket(k: int) -> int:
    b = 1
    while b < k:
        b *= 2
    return b


class KVPool:
    """Block-granular cross-request KV pool over one engine's cache
    layout. Built via :func:`llm_consensus_tpu.kv.pool_for` (one pool
    per engine — arenas are layout-specific); thread-safe."""

    def __init__(self, cfg, *, dtype, kv_quant, shard_fn, place, max_seq,
                 block_size: int, budget_bytes: float):
        from llm_consensus_tpu.models import init_kv_cache

        self.cfg = cfg
        self.block_size = block_size
        self.max_seq = max_seq
        self._dtype = dtype
        self._kv_quant = kv_quant
        self._place = place
        # Per-token KV bytes across both stacks (codes + scales for int8
        # caches) — the arena sizing unit, also exported for the bench's
        # resident-stream capacity model.
        itemsize = jnp.dtype(dtype).itemsize
        per_tok = 2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim
        if kv_quant == "int8":
            self.bytes_per_token = per_tok + 2 * cfg.n_layers * cfg.n_kv_heads * itemsize
        else:
            self.bytes_per_token = per_tok * itemsize
        n_blocks = int(budget_bytes // (block_size * self.bytes_per_token))
        self.n_blocks = max(4, n_blocks)
        arena = init_kv_cache(
            cfg, batch=1, max_seq=self.n_blocks * block_size,
            dtype=dtype, quant=kv_quant,
        )
        if shard_fn is not None:
            arena = shard_fn(arena)
        # One pool lock serializes radix walks, slot accounting, and
        # device dispatch; the guarded-by annotations below are enforced
        # by the static guarded-state checker (analysis/guarded_state.py)
        # and, under LLMC_SANITIZE=1, the named lock joins the runtime
        # lock-order graph (analysis/sanitizer.py).
        self._lock = sanitizer.make_lock("kv.pool")
        self._arena = arena  # guarded by: _lock
        self._free = list(range(self.n_blocks))  # guarded by: _lock
        from llm_consensus_tpu.kv.radix import RadixIndex

        self._radix = RadixIndex(block_size)  # guarded by: _lock
        # Fault injection + telemetry: bound once like every other
        # subsystem, so disabled runs pay a single None-check.
        from llm_consensus_tpu import faults as _faults
        from llm_consensus_tpu import obs as _obs

        self._faults = _faults.plan()
        self._obs = _obs.recorder()
        # Integrity plane (integrity/core.py): stamps a content digest on
        # every published block and verifies a deterministic sample of
        # gathers against it — None when LLMC_INTEGRITY is off, so the
        # hot paths pay one None-check.
        self._integrity = integrity.plane()
        # Chip-time attribution (obs/attrib): gather/publish dispatch
        # walls book as kv_gather/kv_publish; the arena registers as a
        # modeled HBM component; evictions and the pre-truncation
        # pressure event feed the goodput ledger + watermark sentinel.
        self._attrib = _obs.attrib.ledger()
        if self._attrib is not None:
            self._attrib.update_component(
                f"kv_arena:{cfg.name}",
                int(self.n_blocks * block_size * self.bytes_per_token),
            )
        self._stats = {  # guarded by: _lock
            "lookups": 0, "hits": 0, "hit_tokens": 0, "miss_tokens": 0,
            "published_blocks": 0, "evicted_blocks": 0, "exhausted": 0,
            # Disaggregated serving (engine/handoff.py): blocks that
            # arrived via the cross-mesh handoff rather than a local
            # retain — the /statsz ``kv`` block's handoff-traffic view.
            "handoff_blocks": 0,
            # Integrity plane traffic: gathered blocks digest-verified
            # and blocks whose verify failed (subtree dropped, reuse
            # recomputed — see lookup).
            "verified_blocks": 0, "corrupt_blocks": 0,
        }

    @classmethod
    def for_engine(cls, engine) -> "KVPool":
        block = knobs.get_int("LLMC_KV_POOL_BLOCK")
        budget = knobs.get_float("LLMC_KV_POOL_MB") * 1e6
        return cls(
            engine.cfg, dtype=engine._dtype, kv_quant=engine.kv_quant,
            shard_fn=engine._shard_fn, place=engine._place,
            max_seq=engine.max_seq, block_size=max(1, block),
            budget_bytes=budget,
        )

    # -- cache factory -------------------------------------------------------

    def _fresh_cache(self):
        from llm_consensus_tpu.models import init_kv_cache

        cache = init_kv_cache(
            self.cfg, batch=1, max_seq=self.max_seq, dtype=self._dtype,
            quant=self._kv_quant,
        )
        return cache

    # -- integrity (block content digests) -----------------------------------

    def block_digest(self, cache, start: int, flip_bit: bool = False) -> str:
        """Content digest of the block-sized seq span at ``start`` across
        every leaf of ``cache`` — the unit the copy program moves, so a
        digest stamped from the publish source equals a digest of the
        same span read back from the arena or a gathered cache (exact
        bytes, the byte-identity contract doing double duty). Host-side:
        each leaf's span transfers once; only integrity-on paths call
        this. ``flip_bit`` XORs one bit into the first leaf's host copy —
        the ``bit_flip`` fault's injection point, corrupting the
        host-visible copy at the verification boundary."""
        from llm_consensus_tpu.ops.quant import kv_seq_axis

        bs = self.block_size
        crc = 0
        first = True
        for leaf in jax.tree.leaves(cache):
            ax = kv_seq_axis(leaf)
            sl = [slice(None)] * leaf.ndim
            sl[ax] = slice(start, start + bs)
            blk = jax.device_get(leaf[tuple(sl)])
            if first and flip_bit:
                import numpy as _np

                blk = _np.ascontiguousarray(blk).copy()
                blk.view(_np.uint8).reshape(-1)[0] ^= 1
                first = False
            d = integrity.digest_array(blk)
            crc = integrity.crc32_str(d, crc)
        return f"{crc:08x}"

    # -- lookup (radix match + gather) ---------------------------------------

    def lookup(self, ids: list, min_tokens: int, shard_fn=None):
        """(matched tokens, gathered [1, max_seq] cache) — the pool's
        replacement for the engine's snapshot ``_reusable_prefix``.

        The match is capped at ``len(ids) − 1`` (at least one token must
        prefill to produce next-token logits — the classic invariant)
        and floors to a miss below ``min_tokens`` or when the restored
        prefix plus the chunk-rounded tail would overrun ``max_seq`` —
        the caller's ``reuse_ok`` bound, applied HERE so no gather (a
        full [1, max_seq] cache allocation + device copy) is ever
        dispatched for a reuse the engine would then reject. The classic
        snapshot path returns zero-copy so its late gate is free; the
        pool's is not.
        The returned cache holds exact block bytes at [0, n) and zeros
        beyond — the caller masks at n (``_restore_prefix`` /
        ``_fork_prefix``), which also zeroes the matched tail block's
        junk past the match point.
        """
        bs = self.block_size
        with self._lock:
            self._stats["lookups"] += 1
            n, chain = self._radix.match(list(ids))
            n = min(n, len(ids) - 1)
            k = -(-n // bs) if n > 0 else 0
            chunk = max(1, min_tokens)
            tail_rounded = -(-(len(ids) - n) // chunk) * chunk
            if (n < min_tokens or k == 0 or k * bs > self.max_seq
                    or n + tail_rounded > self.max_seq):
                self._stats["miss_tokens"] += len(ids)
                return 0, None
            lease = chain[:k]
            for b in lease:
                b.refs += 1
            self._stats["hits"] += 1
            self._stats["hit_tokens"] += n
            self._stats["miss_tokens"] += len(ids) - n
            # Dispatch INSIDE the lock: slot recycling relies on host
            # dispatch order (gather-before-republish), and publish
            # DONATES the arena — a gather dispatched outside the lock
            # could capture an arena buffer a concurrent publish has
            # already invalidated. The enqueue is async so the lock is
            # held for µs once programs are warm; the first hit in each
            # pow2 k-bucket pays its XLA compile under the lock (once
            # per process, amortized by LLMC_XLA_CACHE across runs) —
            # the price of keeping donation + ordering trivially sound.
            try:
                t_g = time.monotonic()
                with attrib_tag("kv_gather"):
                    dst = self._fresh_cache()
                    if shard_fn is not None:
                        dst = shard_fn(dst)
                    kb = _kbucket(k)
                    srcs = [b.slot * bs for b in lease]
                    dsts = [i * bs for i in range(k)]
                    pad = kb - k
                    srcs += [srcs[-1]] * pad
                    dsts += [dsts[-1]] * pad
                    dst = _copy_blocks(
                        dst, self._arena,
                        self._place(jnp.asarray(srcs, jnp.int32)),
                        self._place(jnp.asarray(dsts, jnp.int32)),
                        kb, bs,
                    )
                if self._attrib is not None:
                    self._attrib.observe_device(
                        "kv_gather", time.monotonic() - t_g
                    )
            finally:
                for b in lease:
                    b.refs -= 1
            if self._integrity is not None and self._integrity.sample_hit():
                # Sampled gather verification: re-digest the gathered
                # spans (a host-visible read of what the client is about
                # to reuse) against the publish-time digests. A mismatch
                # drops the whole chain from the index and reports a
                # MISS — the caller re-prefills, so reuse is lost but
                # the stream never decodes over corrupt bytes.
                flip = False
                if self._faults is not None:
                    fs = self._faults.fire(
                        "corrupt", surface="kv", model=self.cfg.name
                    )
                    flip = fs is not None and fs.kind == "bit_flip"
                for i, b in enumerate(lease):
                    if b.digest is None:
                        continue  # published before the plane came up
                    self._integrity.check("kv")
                    self._stats["verified_blocks"] += 1
                    got = self.block_digest(
                        dst, i * bs, flip_bit=flip and i == 0
                    )
                    if got != b.digest:
                        self._integrity.failure(
                            "kv",
                            f"gather digest mismatch at slot {b.slot}",
                        )
                        self._stats["corrupt_blocks"] += 1
                        self._free.extend(self._radix.drop(b))
                        return 0, None
        if self._obs is not None:
            self._obs.count("kv.hit_tokens", n)
        return n, dst

    # -- publish (scatter + radix insert) ------------------------------------

    def publish(self, ids: list, cache, source: str = "local") -> "tuple[int, bool]":
        """Scatter ``ids``'s KV blocks from a finished left-aligned
        [1, S] ``cache`` into the arena and index them — the pool's
        replacement for snapshot retention. Incremental: only blocks the
        radix doesn't already hold are written (a repeated prompt costs
        a host walk and nothing on device). Returns ``(blocks written,
        truncated)`` — ``truncated`` is True when exhaustion dropped the
        tail, so the caller can surface degraded reuse per response
        instead of burying it in a lifetime counter — from EVERY source:
        the cross-mesh handoff path (``source="handoff"``,
        engine/handoff.py) reports exhaustion through the same tuple and
        the same obs instant as a local retain, so a disaggregated
        deployment sees ``kv.truncated`` on the response exactly like
        the classic path does.

        Divergence is copy-on-write by construction: the plan writes
        fresh blocks for any span that extends or forks an existing
        chain, and attached blocks are never rewritten — a concurrent
        reader's gathered bytes cannot change under it. When the free
        list runs dry, LRU-unreferenced blocks evict; if nothing is
        evictable the publish truncates (``pool_exhausted``) — the
        prefix that did fit is still servable.
        """
        from llm_consensus_tpu.ops.quant import kv_seq_axis

        bs = self.block_size
        leaf = jax.tree.leaves(cache)[0]
        cache_cap = leaf.shape[kv_seq_axis(leaf)]
        # Publish only whole in-capacity block spans: the slice of a
        # partial tail block still reads [start, start+bs), which must
        # sit inside the source cache.
        n = min(len(ids), (cache_cap // bs) * bs)
        if n < 1:
            return 0, False
        exhausted_inject = False
        squeeze_limit = None
        if self._faults is not None:
            fs = self._faults.fire("kv", model=self.cfg.name)
            if fs is not None:
                if fs.kind == "pool_exhausted":
                    exhausted_inject = True
                elif fs.kind == "evict_storm":
                    with self._lock:
                        freed = self._radix.evict(self.n_blocks)
                        self._free.extend(freed)
                        self._stats["evicted_blocks"] += len(freed)
                    if self._obs is not None and freed:
                        self._obs.count("kv.evicted_blocks", len(freed))
                    if self._attrib is not None and freed:
                        self._attrib.token_event(
                            "evicted_kv", len(freed) * bs
                        )
            # hbm_squeeze (site ``pressure``, phase=publish): the
            # effective arena shrinks to @frac= of its blocks for this
            # publish — same truncation path as real exhaustion, under a
            # pool that LOOKS healthy, which is the governor's signal.
            fs = self._faults.fire(
                "pressure", phase="publish", model=self.cfg.name
            )
            if fs is not None and fs.kind == "hbm_squeeze":
                squeeze_limit = max(
                    0, int(self.n_blocks * float(fs.param("frac", 0.5)))
                )
        wrote = 0
        evicted = 0
        pressure_info = None  # fired AFTER the lock: a sentinel dump
        # (ring serialize + disk write) must not stall concurrent
        # gathers/publishes exactly when the system is under pressure.
        with self._lock:
            node, _base, writes = self._radix.plan_insert(list(ids[:n]))
            if not writes:
                return 0, False
            slots: list[int] = []
            for _ in writes:
                if exhausted_inject:
                    break
                if squeeze_limit is not None and (
                    # used = non-free blocks; slots already popped this
                    # publish are no longer in the free list, so they
                    # are counted here exactly once.
                    self.n_blocks - len(self._free) >= squeeze_limit
                ):
                    break  # the squeezed arena has no slot to grant
                if not self._free:
                    freed = self._radix.evict(
                        max(1, len(writes) - len(slots))
                    )
                    evicted += len(freed)
                    self._stats["evicted_blocks"] += len(freed)
                    self._free.extend(freed)
                if not self._free:
                    break
                slots.append(self._free.pop())
            if len(slots) < len(writes):
                # Arena exhausted (every block interior or leased, an
                # injected fault, or a squeezed arena): publish the
                # prefix that fits — chains must stay gap-free, so the
                # tail past the last granted slot is dropped, never
                # skipped over.
                if self._attrib is not None:
                    # HBM watermark sentinel — the instant + dump fire
                    # right after this lock releases, before the caller
                    # can observe the truncation it reports.
                    pressure_info = {
                        "wanted": len(writes), "granted": len(slots),
                        "blocks_total": self.n_blocks,
                        "blocks_free": len(self._free),
                    }
                self._stats["exhausted"] += 1
                truncated = True
                if self._obs is not None:
                    self._obs.instant(
                        "kv_pool_exhausted", tid="kv",
                        wanted=len(writes), granted=len(slots),
                        source=source,
                    )
                    self._obs.count("kv.exhausted")
                writes = writes[:len(slots)]
            else:
                truncated = False
            if writes:
                k = len(writes)
                kb = _kbucket(k)
                srcs = [start for start, _ in writes]
                dsts = [slot * bs for slot in slots]
                pad = kb - k
                srcs += [srcs[-1]] * pad
                dsts += [dsts[-1]] * pad
                t_p = time.monotonic()
                with warnings.catch_warnings(), attrib_tag("kv_publish"):
                    # The arena is long-lived and referenced by in-flight
                    # gathers; donation is for the in-place fast path,
                    # and XLA falling back to a copy when a gather still
                    # holds the buffer is correct — just quiet.
                    warnings.filterwarnings(
                        "ignore",
                        message="Some donated buffers were not usable",
                    )
                    self._arena = _copy_blocks(
                        self._arena, cache,
                        self._place(jnp.asarray(srcs, jnp.int32)),
                        self._place(jnp.asarray(dsts, jnp.int32)),
                        kb, bs,
                    )
                if self._attrib is not None:
                    self._attrib.observe_device(
                        "kv_publish", time.monotonic() - t_p
                    )
                # Attach only AFTER the scatter is enqueued. The pool
                # lock already serializes publish against matches;
                # keeping the ordering anyway means no lease can ever
                # cover bytes that are not at least in flight to the
                # arena (in-order device streams do the rest) — an
                # invariant that holds regardless of how this lock is
                # ever split. attach() re-validating the plan is likewise
                # the index guarding itself (under this lock its dedup
                # branch is unreachable; tests drive it directly) —
                # deduped writes hand their slots back.
                attached = self._radix.attach(node, writes, slots)
                used = {b.slot for b in attached}
                for slot in slots:
                    if slot not in used:
                        self._free.append(slot)
                if self._integrity is not None and attached:
                    # Stamp each attached block's content digest from
                    # the publish SOURCE (the finished cache) — the
                    # scatter moves exact bytes, so a later gather of
                    # the same span must reproduce this digest or the
                    # bytes were corrupted in between.
                    starts = {
                        slot: start
                        for (start, _t), slot in zip(writes, slots)
                    }
                    for blk in attached:
                        blk.digest = self.block_digest(
                            cache, starts[blk.slot]
                        )
                wrote = len(attached)
                self._stats["published_blocks"] += wrote
                if source == "handoff":
                    self._stats["handoff_blocks"] += wrote
        if pressure_info is not None:
            self._attrib.hbm_pressure(
                f"kv_pool:{self.cfg.name}", **pressure_info
            )
        if self._obs is not None:
            if wrote:
                self._obs.count("kv.published_blocks", wrote)
            if evicted:
                self._obs.count("kv.evicted_blocks", evicted)
        if self._attrib is not None and evicted:
            # Goodput ledger: tokens whose KV was computed, published,
            # and then dropped — the recompute exposure of eviction.
            self._attrib.token_event("evicted_kv", evicted * bs)
        return wrote, truncated

    def evict_cold(self, target_occupancy: float) -> int:
        """Evict cold (unreferenced, LRU) blocks until arena occupancy
        is at or below ``target_occupancy`` — the pressure governor's
        ``evict`` rung: trade future prefix reuse for admission headroom
        BEFORE anything user-visible degrades. Returns blocks freed
        (possibly fewer than asked when the remainder is leased or
        interior). No device work: eviction only recycles slots."""
        target = min(1.0, max(0.0, float(target_occupancy)))
        with self._lock:
            used = self.n_blocks - len(self._free)
            want = used - int(target * self.n_blocks)
            if want <= 0:
                return 0
            freed = self._radix.evict(want)
            self._free.extend(freed)
            self._stats["evicted_blocks"] += len(freed)
        if self._obs is not None and freed:
            self._obs.count("kv.evicted_blocks", len(freed))
        if self._attrib is not None and freed:
            self._attrib.token_event(
                "evicted_kv", len(freed) * self.block_size
            )
        return len(freed)

    def covers(self, ids: list) -> bool:
        """True when the radix already holds ``ids``'s whole-block span —
        the admission wave's gate before paying the row-0 extraction
        copy (the classic path's ``_prefix_ids != rows[0]`` analog).

        Judged on whole blocks DELIBERATELY: publish CAN store a partial
        tail (single-stream ``_retain_prefix`` does routinely), but for a
        repeat wave the only delta past the covered span is a sub-block
        tail of < block_size tokens — re-extracting the whole row-0 cache
        every wave to capture it costs more than the ≤ block_size−1
        tokens of prefill a future match would save, so such waves skip
        retention and that tail stays unpublished."""
        n = (len(ids) // self.block_size) * self.block_size
        if n < 1:
            return True
        with self._lock:
            return self._radix.covered(list(ids[:n])) >= n

    def match_len(self, ids: list) -> int:
        """Radix-resident prefix length of ``ids`` — a host-only trie
        walk, no lease, no gather. Admission planning consults this to
        size a wave's shared prefix to what the pool can restore nearly
        for free (the establishment prefill then rides the gather)."""
        with self._lock:
            n, _chain = self._radix.match(list(ids))
        return n

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        """Occupancy + traffic counters for /statsz and metrics.json."""
        with self._lock:
            used = self.n_blocks - len(self._free)
            out = dict(self._stats)
        out.update(
            block_size=self.block_size,
            blocks_total=self.n_blocks,
            blocks_used=used,
            occupancy=round(used / max(1, self.n_blocks), 4),
            bytes_per_token=self.bytes_per_token,
        )
        return out
