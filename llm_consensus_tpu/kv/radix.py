"""Block-granular radix index: token-id trie → arena block chains.

Host-side only (no device ops — the pool owns those), so every structural
invariant is unit-testable without JAX. One node owns exactly ONE block
(`block_size` tokens, the tree's granule): a 4k-token prompt is a ~64-node
chain at the default 64-token block, which keeps splits trivial — a chain
that diverges mid-stream shares the common prefix NODES and branches,
so there is never an edge to split token-by-token.

Invariants:

  * Every block is FULL (``block_size`` tokens) except a chain's tail,
    and a partial block is always a LEAF — a node acquires children only
    once its block is full (`insert` enforces this by never descending
    through a partial block).
  * Blocks are immutable once attached: divergent or extended tails get
    FRESH sibling blocks (copy-on-write at chain level — the shared full
    blocks stay shared through the trie structure; the old tail keeps
    its bytes for whoever still matches it). Nothing ever rewrites an
    attached block's arena slot, so a concurrent reader's gathered bytes
    cannot change under it.
  * ``refs`` counts active leases (a match whose blocks are being
    gathered). Eviction only frees leaf blocks with ``refs == 0``, LRU
    by a monotonic touch stamp bumped on every match/insert along the
    path — interior nodes become evictable as their subtrees drain.

Match is overlap-maximal: full blocks compare exactly; the final block
of a walk contributes its longest common prefix with the query, so a
request that diverges mid-block still reuses every matching token (the
pool masks gathered positions ≥ the matched length, exactly like the
classic snapshot restore).
"""

from __future__ import annotations

from typing import Callable, Optional


class Block:
    """One arena block: its slot, the token ids whose KV it holds, and
    the number of active leases pinning it against eviction."""

    __slots__ = ("slot", "tokens", "refs", "digest")

    def __init__(self, slot: int, tokens: tuple):
        self.slot = slot
        self.tokens = tokens
        self.refs = 0
        # Content digest of the block's arena bytes, stamped by the pool
        # at publish when the integrity plane is on (None otherwise).
        # Immutable like the bytes it covers.
        self.digest: Optional[str] = None

    def __repr__(self) -> str:  # debugging/test output only
        return f"Block(slot={self.slot}, n={len(self.tokens)}, refs={self.refs})"


class _Node:
    __slots__ = ("block", "children", "parent", "stamp")

    def __init__(self, block: Optional[Block], parent: "Optional[_Node]",
                 stamp: int):
        self.block = block          # None only at the root
        self.children: list[_Node] = []
        self.parent = parent
        self.stamp = stamp


class RadixIndex:
    """Token-id trie over block chains. NOT thread-safe — the pool holds
    its lock across every call (match/insert/evict are microseconds of
    pure-Python list walks)."""

    def __init__(self, block_size: int):
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.bs = block_size
        self.root = _Node(None, None, 0)
        self._clock = 0
        self.entries = 0  # attached blocks (== nodes below the root)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # -- match ---------------------------------------------------------------

    def match(self, ids: list) -> tuple[int, list[Block]]:
        """Longest stored prefix of ``ids``: (token count, block chain).

        Whole blocks must match exactly to descend; the last chain block
        contributes its partial overlap. The returned blocks cover
        exactly the matched tokens (the final one possibly partially) —
        the caller leases them (``refs += 1``) before releasing the
        index lock if it intends to gather.
        """
        node = self.root
        n = 0
        out: list[Block] = []
        stamp = self._tick()
        while True:
            best_child: Optional[_Node] = None
            best_overlap = 0
            for child in node.children:
                bt = child.block.tokens
                lim = min(len(bt), len(ids) - n)
                m = 0
                while m < lim and bt[m] == ids[n + m]:
                    m += 1
                if m > best_overlap:
                    best_overlap, best_child = m, child
            if best_child is None:
                return n, out
            best_child.stamp = stamp
            out.append(best_child.block)
            n += best_overlap
            if best_overlap < len(best_child.block.tokens) or (
                best_overlap < self.bs
            ):
                # Partial use of this block (divergence, query exhausted,
                # or a partial tail): the walk ends here.
                return n, out
            node = best_child

    def covered(self, ids: list) -> int:
        """Tokens of ``ids`` already stored verbatim along one chain —
        ``insert`` would write nothing when this equals ``len(ids)``
        (or leaves only a shorter partial tail than an existing one).
        Pure read: no stamps move."""
        node, n = self._walk_full(ids)
        best_tail = 0
        for child in node.children:
            bt = child.block.tokens
            lim = min(len(bt), len(ids) - n)
            if bt[:lim] == tuple(ids[n:n + lim]):
                best_tail = max(best_tail, lim)
        return n + best_tail

    # -- insert --------------------------------------------------------------

    def _walk_full(self, ids: list) -> tuple[_Node, int]:
        """Descend exact FULL-block matches only (the block-aligned
        attach point for an insert). Returns (node, tokens covered)."""
        node = self.root
        n = 0
        while len(ids) - n >= self.bs:
            want = tuple(ids[n:n + self.bs])
            nxt = None
            for child in node.children:
                if len(child.block.tokens) == self.bs and \
                        child.block.tokens == want:
                    nxt = child
                    break
            if nxt is None:
                break
            node = nxt
            n += self.bs
        return node, n

    def plan_insert(self, ids: list) -> tuple[_Node, int, list[tuple]]:
        """What an insert of ``ids`` must write: (attach node, covered
        tokens, [(token start, token tuple)] per NEW block, in chain
        order). Does NOT mutate the tree — the pool allocates slots and
        dispatches the scatter first, then calls :meth:`attach`, so the
        index never holds a block whose bytes are not at least in
        flight to the arena (true by ordering alone, independent of the
        caller's locking).

        Copy-on-write falls out here: when ``ids`` extends or diverges
        from an existing partial tail, the plan writes fresh blocks for
        the whole divergent span and the old tail stays attached
        untouched — no attached block is ever rewritten.
        """
        node, n = self._walk_full(ids)
        node_stamp = self._tick()
        cur = node
        while cur is not None:
            cur.stamp = node_stamp
            cur = cur.parent
        # An existing tail that already covers our remainder (equal or
        # longer overlap) makes the insert a no-op past n.
        rest = len(ids) - n
        if rest <= 0:
            return node, n, []
        for child in node.children:
            bt = child.block.tokens
            if len(bt) >= rest and bt[:rest] == tuple(ids[n:]):
                child.stamp = node_stamp
                return node, n, []
        writes = []
        start = n
        while start < len(ids):
            end = min(start + self.bs, len(ids))
            writes.append((start, tuple(ids[start:end])))
            start = end
        return node, n, writes

    def attach(self, node: _Node, writes: list[tuple], slots: list[int],
               ) -> list[Block]:
        """Attach freshly scattered blocks as a chain under ``node``.

        ``slots[i]`` is the arena slot ``writes[i]`` was scattered to.
        Re-validates the attach point: if another insert attached an
        identical chain between plan and attach, the duplicate full
        blocks dedup onto the existing nodes and only the genuinely new
        tail attaches. The index assumes nothing about caller locking —
        KVPool holds one lock across plan→attach so the dedup branch
        never fires there, but the guard keeps plan/attach safe to
        interleave on its own terms (tests drive it directly). Returns
        the blocks actually attached; slots of deduped writes are NOT
        consumed and the caller returns them to the free list.
        """
        stamp = self._tick()
        attached: list[Block] = []
        parent = node
        for (start, tokens), slot in zip(writes, slots):
            dup = None
            if len(tokens) == self.bs:
                for child in parent.children:
                    if child.block.tokens == tokens:
                        dup = child
                        break
            if dup is not None:
                dup.stamp = stamp
                parent = dup
                continue
            blk = Block(slot, tokens)
            child = _Node(blk, parent, stamp)
            parent.children.append(child)
            self.entries += 1
            attached.append(blk)
            parent = child
        return attached

    # -- containment ---------------------------------------------------------

    def drop(self, block: Block) -> list[int]:
        """Detach the node holding ``block`` plus its entire subtree and
        return their arena slots — the integrity plane's containment for
        a digest-mismatched gather. Descendant blocks' bytes may well be
        fine, but a chain is only reachable through its prefix, so the
        whole subtree returns to the free list and the next request
        re-prefills (reuse lost, never correctness). Called under the
        pool lock, where leases are only ever held transiently inside a
        single ``lookup`` — so unlike ``evict`` there is nothing to pin
        against."""
        target: Optional[_Node] = None
        stack = [self.root]
        while stack and target is None:
            cur = stack.pop()
            for child in cur.children:
                if child.block is block:
                    target = child
                    break
                stack.append(child)
        if target is None:
            return []
        target.parent.children.remove(target)
        freed: list[int] = []
        sub = [target]
        while sub:
            cur = sub.pop()
            self.entries -= 1
            freed.append(cur.block.slot)
            sub.extend(cur.children)
        return freed

    # -- eviction ------------------------------------------------------------

    def evict(self, need: int,
              on_evict: Optional[Callable[[Block], None]] = None,
              ) -> list[int]:
        """Free up to ``need`` arena slots, LRU leaves first.

        Only leaves (no children) with ``refs == 0`` are candidates —
        an interior block is load-bearing for its subtree and a leased
        block is mid-gather. Removing a leaf can expose its parent, so
        freed parents join the candidate heap with their own stamps:
        ONE trie walk + a heap serves any ``need`` (the pool holds its
        lock across this call — an evict_storm over a many-thousand
        block arena must not go quadratic under it). Returns the freed
        slots (oldest stamps first).
        """
        import heapq

        heap: list[tuple[int, int, _Node]] = []
        stack = [self.root]
        while stack:
            cur = stack.pop()
            for child in cur.children:
                if child.children:
                    stack.append(child)
                elif child.block.refs == 0:
                    heapq.heappush(heap, (child.stamp, id(child), child))
        freed: list[int] = []
        while heap and len(freed) < need:
            _, _, victim = heapq.heappop(heap)
            victim.parent.children.remove(victim)
            self.entries -= 1
            freed.append(victim.block.slot)
            if on_evict is not None:
                on_evict(victim.block)
            parent = victim.parent
            if parent is not self.root and not parent.children and \
                    parent.block.refs == 0:
                heapq.heappush(heap, (parent.stamp, id(parent), parent))
        return freed
