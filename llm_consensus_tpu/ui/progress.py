"""Live terminal progress display for concurrent model queries.

Parity: /root/reference/internal/ui/ui.go:30-259. Per-model state machine
Pending → Running → Streaming → Complete/Failed; a background thread repaints
every 100 ms by cursor-up + clear-line; token estimate = chars/4; braille
spinner keyed to wall clock.
"""

from __future__ import annotations

import enum
import threading
import time
from dataclasses import dataclass, field
from typing import IO, Optional

from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.ui import ansi

REPAINT_INTERVAL = 0.1  # seconds (ui.go:92)
SPINNER_FRAMES = ["⠋", "⠙", "⠹", "⠸", "⠼", "⠴", "⠦", "⠧", "⠇", "⠏"]  # ui.go:246


class ModelStatus(enum.Enum):
    PENDING = "pending"
    RUNNING = "running"
    STREAMING = "streaming"
    COMPLETE = "complete"
    FAILED = "failed"


@dataclass
class ModelState:
    """State of a single model query (ui.go:41-50)."""

    model: str
    status: ModelStatus = ModelStatus.PENDING
    start_time: float = 0.0
    end_time: float = 0.0
    error: Optional[BaseException] = None
    char_count: int = 0
    token_est: int = 0


def spinner(now: Optional[float] = None) -> str:
    """Spinner frame keyed to wall-clock milliseconds (ui.go:245-249)."""
    if now is None:
        now = time.time()
    return SPINNER_FRAMES[int(now * 1000 / 100) % len(SPINNER_FRAMES)]


def truncate(s: str, max_len: int) -> str:
    """Single-line truncation with ellipsis (ui.go:252-259)."""
    s = " ".join(s.split("\n")).strip()
    if len(s) > max_len:
        return s[: max_len - 1] + "…"
    return s


class Progress:
    """Real-time progress of N model queries (ui.go:53-106)."""

    def __init__(self, w: IO[str], models: list[str], quiet: bool = False):
        self._w = w
        self._order = list(models)
        self._models = {m: ModelState(model=m) for m in models}
        self._start_time = time.monotonic()
        self._quiet = quiet
        self._lock = sanitizer.make_lock("ui.progress")
        self._stop_event = sanitizer.make_event("ui.progress.stop")
        self._thread: Optional[threading.Thread] = None
        self._rendered = False

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._quiet:
            return
        self._render()
        self._thread = threading.Thread(target=self._loop, name="progress", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop_event.wait(REPAINT_INTERVAL):
            self._render()

    def stop(self) -> None:
        if self._quiet:
            return
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join()
        with self._lock:
            if self._rendered:
                self._clear_lines(len(self._order) + 2)

    # -- state transitions (ui.go:124-168) ----------------------------------

    def model_started(self, model: str) -> None:
        with self._lock:
            state = self._models.get(model)
            if state:
                state.status = ModelStatus.RUNNING
                state.start_time = time.monotonic()

    def model_streaming(self, model: str, chunk: str) -> None:
        with self._lock:
            state = self._models.get(model)
            if state:
                state.status = ModelStatus.STREAMING
                state.char_count += len(chunk)
                state.token_est = state.char_count // 4  # ~4 chars per token (ui.go:142)

    def model_completed(self, model: str) -> None:
        with self._lock:
            state = self._models.get(model)
            if state:
                state.status = ModelStatus.COMPLETE
                state.end_time = time.monotonic()

    def model_failed(self, model: str, error: BaseException) -> None:
        with self._lock:
            state = self._models.get(model)
            if state:
                state.status = ModelStatus.FAILED
                state.end_time = time.monotonic()
                state.error = error

    # -- rendering (ui.go:171-242) ------------------------------------------

    def _render(self) -> None:
        with self._lock:
            if self._rendered:
                self._clear_lines(len(self._order) + 2)
            self._rendered = True

            elapsed = time.monotonic() - self._start_time
            self._w.write(
                f"{ansi.BOLD_CYAN}⚡ Querying {len(self._order)} models{ansi.RESET} "
                f"{ansi.DIM}({elapsed:.1f}s){ansi.RESET}\n"
            )
            for model in self._order:
                self._render_model_line(self._models[model])
            self._w.write("\n")
            self._w.flush()

    def _render_model_line(self, state: ModelState) -> None:
        now = time.monotonic()
        if state.status is ModelStatus.PENDING:
            icon, color, status = "○", ansi.DIM, "pending"
        elif state.status is ModelStatus.RUNNING:
            icon, color = spinner(), ansi.YELLOW
            status = f"connecting... {now - state.start_time:.1f}s"
        elif state.status is ModelStatus.STREAMING:
            icon, color = spinner(), ansi.CYAN
            status = f"streaming ~{state.token_est} tokens {now - state.start_time:.1f}s"
        elif state.status is ModelStatus.COMPLETE:
            icon, color = "✓", ansi.GREEN
            status = f"done ~{state.token_est} tokens in {state.end_time - state.start_time:.1f}s"
        else:
            icon, color = "✗", ansi.RED
            status = f"failed: {state.error}"

        name = truncate(state.model, 25)
        self._w.write(f"  {color}{icon}{ansi.RESET} {name:<25} {color}{status}{ansi.RESET}\n")

    def _clear_lines(self, n: int) -> None:
        self._w.write(ansi.CURSOR_UP_CLEAR * n)
