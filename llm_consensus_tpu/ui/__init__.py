from llm_consensus_tpu.ui.progress import ModelState, ModelStatus, Progress
from llm_consensus_tpu.ui.printers import (
    is_terminal,
    print_aggregate,
    print_consensus,
    print_error,
    print_header,
    print_model_response,
    print_phase,
    print_serve_banner,
    print_success,
    print_summary,
    print_throughput,
)

__all__ = [
    "ModelState",
    "ModelStatus",
    "Progress",
    "is_terminal",
    "print_aggregate",
    "print_consensus",
    "print_error",
    "print_header",
    "print_model_response",
    "print_phase",
    "print_serve_banner",
    "print_success",
    "print_summary",
    "print_throughput",
]
