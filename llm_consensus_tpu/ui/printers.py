"""Static pretty-printers for the CLI (parity: ui.go:262-322)."""

from __future__ import annotations

import os
import stat
from typing import IO

from llm_consensus_tpu.ui import ansi
from llm_consensus_tpu.ui.progress import truncate


def print_header(w: IO[str], prompt: str) -> None:
    """Header box with truncated prompt (ui.go:262-267)."""
    w.write(f"\n{ansi.BOLD_CYAN}╭─ LLM Consensus ─╮{ansi.RESET}\n")
    w.write(f"{ansi.CYAN}│{ansi.RESET} Prompt: {ansi.DIM}{truncate(prompt, 60)}{ansi.RESET}\n")
    w.write(f"{ansi.CYAN}╰─────────────────╯{ansi.RESET}\n\n")


def print_phase(w: IO[str], phase: str) -> None:
    w.write(f"{ansi.BOLD_YELLOW}▸ {phase}{ansi.RESET}\n")


def print_success(w: IO[str], msg: str) -> None:
    w.write(f"{ansi.GREEN}✓ {msg}{ansi.RESET}\n")


def print_error(w: IO[str], msg: str) -> None:
    w.write(f"{ansi.RED}✗ {msg}{ansi.RESET}\n")


def print_model_response(
    w: IO[str], model: str, provider: str, content: str, latency_ms: float
) -> None:
    """Per-model response box (ui.go:285-295)."""
    w.write(f"\n{ansi.BLUE}┌─ {model} ({provider}) [{latency_ms / 1000:.1f}s] ─┐{ansi.RESET}\n")
    for line in content.split("\n"):
        w.write(f"{ansi.BLUE}│{ansi.RESET} {line}\n")
    w.write(f"{ansi.BLUE}└─────────────────────────┘{ansi.RESET}\n")


def print_consensus(w: IO[str], consensus: str) -> None:
    """Consensus box (ui.go:298-306)."""
    w.write(f"\n{ansi.BOLD_GREEN}╔═══ CONSENSUS ═══╗{ansi.RESET}\n")
    for line in consensus.split("\n"):
        w.write(f"{ansi.GREEN}║{ansi.RESET} {line}\n")
    w.write(f"{ansi.GREEN}╚═════════════════╝{ansi.RESET}\n")


def print_summary(
    w: IO[str], total_models: int, successful: int, failed: int, total_seconds: float
) -> None:
    """Run summary (ui.go:309-316)."""
    w.write(f"\n{ansi.DIM}─── Summary ───{ansi.RESET}\n")
    w.write(
        f"Models queried: {total_models} "
        f"({ansi.GREEN}{successful} succeeded{ansi.RESET}, "
        f"{ansi.RED}{failed} failed{ansi.RESET})\n"
    )
    w.write(f"Total time: {total_seconds:.1f}s\n")


def print_throughput(w: IO[str], responses) -> None:
    """On-device throughput lines (TPU-build extension; no reference analog).

    Prints one line per response carrying real decode measurements — token
    count, steady-state tokens/sec, and decode MFU when the chip's peak is
    known. Responses without stats (HTTP providers, too-short runs) are
    skipped; prints nothing when no response has stats.
    """
    stats = [r for r in responses if getattr(r, "tokens_per_sec", None)]
    if not stats:
        return
    w.write(f"\n{ansi.DIM}─── Throughput (on-device) ───{ansi.RESET}\n")
    for r in stats:
        line = f"{r.model}: {r.tokens} tokens, {r.tokens_per_sec:.1f} tok/s"
        if getattr(r, "mbu", None) is not None:
            line += f", {r.mbu * 100:.0f}% MBU"
        if r.mfu is not None:
            line += f", {r.mfu * 100:.1f}% MFU"
        w.write(line + "\n")


def print_aggregate(w: IO[str], aggregate) -> None:
    """Pool-wide throughput footer from the run recorder's aggregate
    (obs/export.aggregate_throughput; TPU-build extension, no reference
    analog).

    One line: tokens over the union of the run's decode activity window,
    plus the token-weighted mean MFU when chips reported one. Statless
    runs — HTTP-only panels, recorder disabled, runs too short to
    measure — pass None and print nothing, matching ``print_throughput``.
    """
    if not aggregate:
        return
    tokens = aggregate.get("tokens", 0.0)
    rate = aggregate.get("tokens_per_sec", 0.0)
    if not tokens or not rate:
        return
    line = f"Pool: {int(tokens)} tokens, {rate:.1f} tok/s"
    mfu = aggregate.get("mfu")
    if mfu:
        line += f", {mfu * 100:.1f}% MFU"
    w.write(f"{ansi.DIM}{line}{ansi.RESET}\n")


def print_serve_banner(
    w: IO[str],
    host: str,
    port: int,
    models: list[str],
    judge: str,
    *,
    max_concurrency: int,
    max_batch: int,
) -> None:
    """Startup banner for ``llm-consensus serve`` (TPU-build extension)."""
    w.write(f"\n{ansi.BOLD_CYAN}╭─ LLM Consensus — serving ─╮{ansi.RESET}\n")
    w.write(f"{ansi.CYAN}│{ansi.RESET} http://{host}:{port}/v1/consensus\n")
    w.write(f"{ansi.CYAN}│{ansi.RESET} panel: {ansi.DIM}{', '.join(models)}{ansi.RESET}\n")
    w.write(f"{ansi.CYAN}│{ansi.RESET} judge: {ansi.DIM}{judge}{ansi.RESET}\n")
    w.write(
        f"{ansi.CYAN}│{ansi.RESET} capacity: {max_concurrency} concurrent "
        f"runs, {max_batch} batcher slots/preset\n"
    )
    w.write(f"{ansi.CYAN}╰───────────────────────────╯{ansi.RESET}\n")


def is_terminal(f) -> bool:
    """Char-device check (ui.go:319-322)."""
    try:
        mode = os.fstat(f.fileno()).st_mode
    except (OSError, ValueError, AttributeError):
        return False
    return stat.S_ISCHR(mode)
