"""Next-token cross-entropy loss.

fp32 end to end: logits already leave the model in fp32
(models/transformer.py final einsum uses ``preferred_element_type``), and
the log-softmax + gather stay there — bf16 loss math loses enough mantissa
to visibly bend small-model loss curves.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def cross_entropy_loss(
    logits: jax.Array,              # [B, T, V] fp32
    targets: jax.Array,             # [B, T] int32
    mask: Optional[jax.Array] = None,  # [B, T] 1.0 = count this position
) -> jax.Array:
    """Mean token cross-entropy over masked positions (scalar fp32).

    ``targets`` are already shifted by the caller (targets[t] is the token
    that should follow inputs[t]); padding positions carry mask 0.
    """
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        return jnp.mean(nll)
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def distill_loss(
    logits: jax.Array,                 # [B, T, V] student, fp32
    teacher_logits: jax.Array,         # [B, T, V] teacher, any float
    targets: jax.Array,                # [B, T] int32 (verdict tokens)
    mask: Optional[jax.Array] = None,  # [B, T] 1.0 = count this position
    *,
    temperature: float = 2.0,
    alpha: float = 0.5,
) -> "tuple[jax.Array, dict]":
    """``alpha * KL(teacher‖student) + (1-alpha) * CE(targets)``.

    The soft half is the classic temperature-scaled distillation KL:
    both distributions soften at ``T`` and the KL term carries the
    ``T^2`` gradient-scale correction, so ``alpha`` trades the two
    halves off on comparable footing at any temperature. The hard half
    is :func:`cross_entropy_loss` on the journaled verdict tokens.
    ``mask`` gates BOTH halves — prompt positions and padding are dead
    for soft and hard targets alike (the student is graded on judging,
    not on modeling the panel prompt). Teacher logits pass through
    ``stop_gradient``: the teacher is a frozen reference, whatever
    params produced it.

    Returns ``(loss, aux)`` with ``aux = {"kl": ..., "ce": ...}`` so the
    train step can report both halves without recomputing.
    """
    t = float(temperature)
    logits = logits.astype(jnp.float32)
    teacher_logits = jax.lax.stop_gradient(
        teacher_logits.astype(jnp.float32)
    )
    logp_s = jax.nn.log_softmax(logits / t, axis=-1)
    logp_t = jax.nn.log_softmax(teacher_logits / t, axis=-1)
    p_t = jnp.exp(logp_t)
    kl_tok = jnp.sum(p_t * (logp_t - logp_s), axis=-1)  # [B, T]
    if mask is None:
        kl = jnp.mean(kl_tok)
    else:
        m = mask.astype(jnp.float32)
        kl = jnp.sum(kl_tok * m) / jnp.maximum(jnp.sum(m), 1.0)
    kl = kl * (t * t)
    ce = cross_entropy_loss(logits, targets, mask)
    a = jnp.float32(alpha)
    loss = a * kl + (1.0 - a) * ce
    return loss, {"kl": kl, "ce": ce}
