"""Next-token cross-entropy loss.

fp32 end to end: logits already leave the model in fp32
(models/transformer.py final einsum uses ``preferred_element_type``), and
the log-softmax + gather stay there — bf16 loss math loses enough mantissa
to visibly bend small-model loss curves.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def cross_entropy_loss(
    logits: jax.Array,              # [B, T, V] fp32
    targets: jax.Array,             # [B, T] int32
    mask: Optional[jax.Array] = None,  # [B, T] 1.0 = count this position
) -> jax.Array:
    """Mean token cross-entropy over masked positions (scalar fp32).

    ``targets`` are already shifted by the caller (targets[t] is the token
    that should follow inputs[t]); padding positions carry mask 0.
    """
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        return jnp.mean(nll)
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
