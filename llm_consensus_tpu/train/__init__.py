"""Training layer: loss, optimizer, and the sharded train step.

The reference framework has no training path at all (it is an HTTP
consensus CLI — SURVEY.md §2); this package exists because a TPU-native
framework that owns its models must also be able to fine-tune them (judge
distillation, panel adapters). It is also the surface the driver's
``dryrun_multichip`` exercises: one jitted train step over a real
dp/tp/sp(/ep/pp) mesh.

Modules:
  loss       — next-token cross-entropy (fp32, masked) + the
               distillation KL/CE mix (flywheel/distill.py's objective)
  step       — TrainState + make_train_step (GSPMD-sharded, remat)
"""

from llm_consensus_tpu.train.loss import cross_entropy_loss, distill_loss
from llm_consensus_tpu.train.step import (
    TrainState,
    init_train_state,
    make_train_step,
)

__all__ = [
    "cross_entropy_loss",
    "distill_loss",
    "TrainState",
    "init_train_state",
    "make_train_step",
]
