"""Sharded train step: GSPMD data/tensor/sequence/expert parallelism.

TPU-first shape of this module:
  * One jitted function is the whole step — forward, backward, optimizer —
    so XLA fuses the lot and schedules collectives (grad all-reduce over
    ``dp``, row-parallel all-reduces over ``tp``, MoE all-to-alls over
    ``ep``) against compute on ICI.
  * Parallelism is declared, not coded: params carry ``param_specs``
    NamedShardings (parallel/sharding.py), the batch is constrained to
    ``P('dp', 'sp')``, and GSPMD derives every collective. There is no
    hand-written gradient synchronization anywhere.
  * ``donate_argnums`` donates the previous state so params + optimizer
    moments are updated in place in HBM (an 8B AdamW state is 3× params —
    without donation the step would double-buffer it).
  * ``remat=True`` checkpoints each scanned layer (models/transformer.py),
    trading recompute for activation memory at long sequence lengths.

The reference has no training story (proof of absence: SURVEY.md §2); this
is new surface owed by a framework that owns its models on-device.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_consensus_tpu.models import forward, init_params
from llm_consensus_tpu.models.config import ModelConfig
from llm_consensus_tpu.parallel.sharding import param_specs, shard_pytree
from llm_consensus_tpu.train.loss import cross_entropy_loss


@flax.struct.dataclass
class TrainState:
    step: jax.Array           # scalar int32
    params: dict
    opt_state: Any            # optax state (mu/nu mirror the params tree)


def default_optimizer(
    lr: float = 3e-4, weight_decay: float = 0.1, clip_norm: float = 1.0
) -> optax.GradientTransformation:
    """AdamW with global-norm clipping — the boring, correct default."""
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def init_train_state(
    cfg: ModelConfig,
    key: jax.Array,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    dtype=jnp.bfloat16,
) -> TrainState:
    """Init params (+ optimizer moments) directly into their mesh placement.

    ``optimizer.init`` runs under jit so the AdamW mu/nu buffers are born
    with the same NamedSharding as their params — no host round-trip, no
    resharding transfer.
    """
    params = init_params(cfg, key, dtype=dtype)
    if mesh is not None:
        params = shard_pytree(params, param_specs(cfg, mesh), mesh)
    opt_state = jax.jit(optimizer.init)(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)


def _batch_spec(mesh: Optional[Mesh]) -> P:
    """[B, T] spec: batch over ``dp``, sequence over ``sp`` where present."""
    if mesh is None:
        return P(None, None)
    dp = "dp" if "dp" in mesh.axis_names and mesh.shape["dp"] > 1 else None
    sp = "sp" if "sp" in mesh.axis_names and mesh.shape["sp"] > 1 else None
    return P(dp, sp)


def make_train_step(
    cfg: ModelConfig,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    remat: bool = True,
):
    """Build the jitted train step.

    Returns ``step_fn(state, batch) -> (state, metrics)`` where ``batch``
    is ``{"tokens", "targets", "mask"}`` each [B, T] and metrics carries
    scalar fp32 ``loss`` and ``grad_norm``.
    """
    spec = _batch_spec(mesh)

    def train_step(state: TrainState, batch: dict):
        if mesh is not None:
            batch = {
                k: jax.lax.with_sharding_constraint(v, NamedSharding(mesh, spec))
                for k, v in batch.items()
            }

        def loss_fn(params):
            logits, _ = forward(params, cfg, batch["tokens"], remat=remat)
            return cross_entropy_loss(logits, batch["targets"], batch.get("mask"))

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, "grad_norm": optax.global_norm(grads)}
        new_state = TrainState(step=state.step + 1, params=params, opt_state=opt_state)
        return new_state, metrics

    return jax.jit(train_step, donate_argnums=0)
