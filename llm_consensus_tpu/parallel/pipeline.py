"""GPipe-style pipeline parallelism via shard_map + ppermute.

Stages are carved from the model's layer-stacked parameter pytree
(models/transformer.py stacks every layer on a leading [L, ...] axis), so
"pipeline stage i" is literally the i-th shard of that axis over mesh axis
``pp`` — no per-stage module surgery, the same params serve TP and PP.

Schedule: classic GPipe. The batch splits into M microbatches; at micro-
step t, stage 0 feeds microbatch t while stage s runs microbatch t-s, and
activations hop stage→stage+1 over ICI with ``ppermute``. A full forward
takes M + S - 1 steps with the usual (S-1)/(M+S-1) bubble; the whole
schedule is one ``lax.scan`` of static collective-permutes, so XLA
overlaps each hop with the next stage's compute and autodiff runs the ring
backwards for free (ppermute's transpose is the reverse permute).

The reference has no model partitioning of any kind (its models are remote
APIs — SURVEY.md §2 "ABSENT" table); this is the PP half of the owed
tensor/pipeline story, composing with TP (sharding.py) on a pp×tp mesh.

Known limitation (v1): microbatch inputs are replicated to every stage and
outputs are broadcast back with a psum, so only the *parameters* shard over
``pp`` — per-stage activation residency is O(B·T·D), not O(B·T·D/S). That
is the right trade while PP's job here is fitting big *weights* (the 70B
judge ladder), and wrong once activations dominate; the v2 schedule should
circulate boundary activations only (stage-0-resident input feed, last-
stage-only collection) before PP is used at training sequence lengths.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from llm_consensus_tpu.models.config import ModelConfig
from llm_consensus_tpu.models.transformer import _layer, embed_tokens, unembed
from llm_consensus_tpu.ops.attention import make_attention_mask
from llm_consensus_tpu.ops.rope import rope_angles, rope_inv_freq
from llm_consensus_tpu.parallel.mesh import pvary


def _pipeline_body(
    layers_local: dict,      # this stage's layer shard: leading dim L/S
    xs: jax.Array,           # [M, mb, T, D] microbatched embeddings (replicated)
    cos: jax.Array,
    sin: jax.Array,
    mask: jax.Array,         # [mb, T, T]
    *,
    cfg: ModelConfig,
    axis_name: str,
) -> jax.Array:
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    m = xs.shape[0]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def apply_stage(x):
        def scan_body(x, lp):
            x, _, _ = _layer(cfg, x, lp, cos, sin, mask, None, None, None)
            return x, None

        x, _ = jax.lax.scan(scan_body, x, layers_local)
        return x

    def step(carry, t):
        recv, ys = carry
        feed = jax.lax.dynamic_index_in_dim(
            xs, jnp.minimum(t, m - 1), 0, keepdims=False
        )
        x = jnp.where(stage == 0, feed, recv)
        out = apply_stage(x)
        # The last stage finishes microbatch t-(S-1) at step t; earlier
        # steps write garbage into slot 0 that step t=S-1 overwrites.
        ys = jax.lax.dynamic_update_index_in_dim(
            ys, out, jnp.clip(t - (n_stages - 1), 0, m - 1), 0
        )
        recv = jax.lax.ppermute(out, axis_name, perm)
        return (recv, ys), None

    zero = jnp.zeros(xs.shape[1:], xs.dtype)
    ys0 = jnp.zeros_like(xs)
    init = (
        pvary(zero, axis_name),
        pvary(ys0, axis_name),
    )
    (_, ys), _ = jax.lax.scan(step, init, jnp.arange(m + n_stages - 1))
    # Only the last stage holds real outputs; zero-mask + psum broadcasts
    # them to every stage so downstream (final norm, logits) stays SPMD.
    ys = jnp.where(stage == n_stages - 1, ys, jnp.zeros_like(ys))
    return jax.lax.psum(ys, axis_name)


def pipeline_forward(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,          # [B, T] int32
    mesh: Mesh,
    axis_name: str = "pp",
    microbatches: int = 4,
) -> jax.Array:
    """Training/eval forward with layers pipelined over ``axis_name``.

    Returns logits [B, T, V] fp32, numerically equal to
    ``models.forward(params, cfg, tokens)`` (same layer math, same order).
    Constraints: n_layers and batch divisible by the stage count and
    microbatch count respectively.
    """
    n_stages = mesh.shape[axis_name]
    if cfg.n_layers % n_stages:
        raise ValueError(f"{cfg.n_layers} layers not divisible by {n_stages} stages")
    b, t = tokens.shape
    if b % microbatches:
        raise ValueError(f"batch {b} not divisible by {microbatches} microbatches")
    mb = b // microbatches

    x = embed_tokens(params, cfg, tokens)

    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None, :], (mb, t))
    inv_freq = rope_inv_freq(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling_dict)
    cos, sin = rope_angles(positions, inv_freq)
    mask = make_attention_mask(positions, positions, None, cfg.sliding_window)

    xs = x.reshape(microbatches, mb, t, cfg.d_model)

    layer_specs = jax.tree.map(lambda _: P(axis_name), params["layers"])
    body = jax.shard_map(
        partial(_pipeline_body, cfg=cfg, axis_name=axis_name),
        mesh=mesh,
        in_specs=(layer_specs, P(), P(), P(), P()),
        out_specs=P(),
    )
    ys = body(params["layers"], xs, cos, sin, mask)

    return unembed(params, cfg, ys.reshape(b, t, cfg.d_model))


def dryrun_pipeline(n_devices: int, devices=None) -> None:
    """One pipelined train step on tiny shapes (driver's pp validation)."""
    import optax

    from llm_consensus_tpu.models import get_config, init_params
    from llm_consensus_tpu.parallel.mesh import make_mesh
    from llm_consensus_tpu.train.loss import cross_entropy_loss

    devices = list(devices if devices is not None else jax.devices())[:n_devices]
    # Stage count = largest power of two ≤ n_devices that divides n_layers.
    cfg = get_config("tiny-llama", n_layers=8)
    pp = 1
    while pp * 2 <= min(n_devices, cfg.n_layers) and cfg.n_layers % (pp * 2) == 0:
        pp *= 2
    mesh = make_mesh({"pp": pp}, devices[:pp])

    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size, jnp.int32
    )
    targets = jnp.roll(tokens, -1, axis=1)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def train_step(params, opt_state):
        def loss_fn(p):
            logits = pipeline_forward(p, cfg, tokens, mesh, microbatches=4)
            return cross_entropy_loss(logits, targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    params, opt_state, loss = train_step(params, opt_state)
    loss = float(loss)
    assert jnp.isfinite(loss), "pipeline: non-finite loss"
    print(f"[dryrun] pipeline pp={pp} microbatches=4 loss={loss:.4f} ok")
