"""GPipe-style pipeline parallelism via shard_map + ppermute.

Stages are carved from the model's layer-stacked parameter pytree
(models/transformer.py stacks every layer on a leading [L, ...] axis), so
"pipeline stage i" is literally the i-th shard of that axis over mesh axis
``pp`` — no per-stage module surgery, the same params serve TP and PP.

Schedule: classic GPipe. The batch splits into M microbatches; at micro-
step t, stage 0 feeds microbatch t while stage s runs microbatch t-s, and
activations hop stage→stage+1 over ICI with ``ppermute``. A full forward
takes M + S - 1 steps with the usual (S-1)/(M+S-1) bubble; the whole
schedule is one ``lax.scan`` of static collective-permutes, so XLA
overlaps each hop with the next stage's compute and autodiff runs the ring
backwards for free (ppermute's transpose is the reverse permute).

The reference has no model partitioning of any kind (its models are remote
APIs — SURVEY.md §2 "ABSENT" table); this is the PP half of the owed
tensor/pipeline story, composing with TP (sharding.py) on a pp×tp mesh.

**v2 schedule — boundary activations only.** v1 replicated all M
microbatch inputs to every stage and psum-broadcast the outputs, so
per-stage activation residency was O(B·T·D) and PP only sharded weights.
v2 shards both ends over the stages: each stage holds c = M/S input
microbatches and c output slots, and three things move per step —

  * the boundary activation hops stage→stage+1 (the pipeline itself);
  * the input queue rotates one stage toward stage 0, so the microbatch
    stage 0 needs at step t (global index t, stored at slot t//S of the
    stage originally holding t%S) arrives exactly on time;
  * the output queue rotates the same way, and the last stage writes
    microbatch g into slot g//S at step g+S-1 — after the remaining
    rotations it lands on stage g%S, mirroring the input layout, so the
    final outputs are stage-sharded with no gather inside the loop.

Per-stage residency is O(B·T·D/S) (the VERDICT r1 #8 criterion); the
cost is that each rotation moves both full queues (c microbatches each)
per step instead of one — 2·(M/S)× the boundary-activation traffic
itself, fully overlappable by XLA with stage compute and worth refining
to per-slot shifts if ICI ever binds. M must divide by S so the queues
are rectangular.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from jax.sharding import Mesh, PartitionSpec as P

from llm_consensus_tpu.utils.jaxcompat import shard_map as _shard_map
from llm_consensus_tpu.models.config import ModelConfig
from llm_consensus_tpu.models.transformer import _layer, embed_tokens, unembed
from llm_consensus_tpu.ops.attention import make_attention_mask
from llm_consensus_tpu.ops.rope import rope_angles, rope_inv_freq
from llm_consensus_tpu.parallel.mesh import pvary


def _pipeline_body(
    layers_local: dict,      # this stage's layer shard: leading dim L/S
    inq: jax.Array,          # [1, c, mb, T, D] — this stage's input queue
    cos: jax.Array,
    sin: jax.Array,
    mask: jax.Array,         # [mb, T, T]
    *,
    cfg: ModelConfig,
    axis_name: str,
    n_microbatches: int,
) -> jax.Array:
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    m = n_microbatches
    c = inq.shape[1]  # microbatches resident per stage (M/S)
    inq = inq[0]
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    perm_back = [(i, (i - 1) % n_stages) for i in range(n_stages)]

    def apply_stage(x):
        def scan_body(x, lp):
            x, _, _ = _layer(cfg, x, lp, cos, sin, mask, None, None, None)
            return x, None

        x, _ = jax.lax.scan(scan_body, x, layers_local)
        return x

    def step(carry, t):
        inq, outq, recv = carry
        # Stage 0 consumes global microbatch t: after t end-of-step
        # rotations, slot t//S of its queue holds exactly that element
        # (clipped reads past M are bubble-tail garbage whose results
        # never reach an output slot).
        feed = jax.lax.dynamic_index_in_dim(
            inq, jnp.clip(t // n_stages, 0, c - 1), 0, keepdims=False
        )
        x = jnp.where(stage == 0, feed, recv)
        out = apply_stage(x)
        # Rotate BEFORE the write: microbatch g (= t-(S-1)) written at
        # slot g//S then rotated T-1-t more times lands on stage g%S —
        # the mirror of the input layout. Pre-real writes (t < S-1) park
        # garbage in slot 0, which later real writes overwrite exactly
        # when their ring positions collide.
        outq = jax.lax.ppermute(outq, axis_name, perm_back)
        write_slot = jnp.clip((t - (n_stages - 1)) // n_stages, 0, c - 1)
        cur = jax.lax.dynamic_index_in_dim(outq, write_slot, 0, keepdims=False)
        newval = jnp.where(stage == n_stages - 1, out, cur)
        outq = jax.lax.dynamic_update_index_in_dim(outq, newval, write_slot, 0)
        # Boundary activation hops forward; the input queue rotates
        # toward stage 0 (end-of-step, so step t sees t rotations).
        recv = jax.lax.ppermute(out, axis_name, perm_fwd)
        inq = jax.lax.ppermute(inq, axis_name, perm_back)
        return (inq, outq, recv), None

    zero = jnp.zeros(inq.shape[1:], inq.dtype)
    init = (
        inq,
        jnp.zeros_like(inq),  # varying by construction (from sharded inq)
        pvary(zero, axis_name),
    )
    (_, outq, _), _ = jax.lax.scan(step, init, jnp.arange(m + n_stages - 1))
    # Outputs end stage-sharded: stage s holds {g : g ≡ s (mod S)} at
    # slot g//S — returned with a leading stage axis, no gather here.
    return outq[None]


def pipeline_forward(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,          # [B, T] int32
    mesh: Mesh,
    axis_name: str = "pp",
    microbatches: Optional[int] = None,
) -> jax.Array:
    """Training/eval forward with layers pipelined over ``axis_name``.

    Returns logits [B, T, V] fp32, numerically equal to
    ``models.forward(params, cfg, tokens)`` (same layer math, same order).
    Constraints: n_layers divisible by the stage count, batch by the
    microbatch count, and microbatches by the stage count (stage-resident
    queues). Default microbatches: max(4, stage count).
    """
    n_stages = mesh.shape[axis_name]
    if microbatches is None:
        # Smallest multiple of the stage count that is >= 4 (the M % S
        # constraint must hold for ANY stage count, including e.g. 3).
        microbatches = n_stages * max(1, -(-4 // n_stages))
    if cfg.n_layers % n_stages:
        raise ValueError(f"{cfg.n_layers} layers not divisible by {n_stages} stages")
    b, t = tokens.shape
    if b % microbatches:
        raise ValueError(f"batch {b} not divisible by {microbatches} microbatches")
    if microbatches % n_stages:
        raise ValueError(
            f"{microbatches} microbatches not divisible by {n_stages} stages "
            "(the v2 schedule keeps M/S microbatches resident per stage)"
        )
    mb = b // microbatches
    c = microbatches // n_stages

    x = embed_tokens(params, cfg, tokens)

    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None, :], (mb, t))
    inv_freq = rope_inv_freq(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling_dict)
    cos, sin = rope_angles(positions, inv_freq)
    mask = make_attention_mask(positions, positions, None, cfg.sliding_window)

    # Stage-sharded input layout: global microbatch g lives on stage
    # g % S at slot g // S — [S, c, mb, T, D] with axis 0 over ``pp``,
    # so each stage holds only its c microbatches (1/S of the batch).
    xs = x.reshape(microbatches, mb, t, cfg.d_model)
    xs = xs.reshape(c, n_stages, mb, t, cfg.d_model).swapaxes(0, 1)

    layer_specs = jax.tree.map(lambda _: P(axis_name), params["layers"])
    body = _shard_map(
        partial(
            _pipeline_body, cfg=cfg, axis_name=axis_name,
            n_microbatches=microbatches,
        ),
        mesh=mesh,
        in_specs=(layer_specs, P(axis_name), P(), P(), P()),
        out_specs=P(axis_name),
    )
    ys = body(params["layers"], xs, cos, sin, mask)

    # Undo the stage-sharded layout: [S, c, ...] → global microbatch
    # order g = slot·S + stage (one resharding collective, outside the
    # pipeline loop).
    ys = ys.swapaxes(0, 1).reshape(b, t, cfg.d_model)
    return unembed(params, cfg, ys)


def dryrun_pipeline(n_devices: int, devices=None) -> None:
    """One pipelined train step on tiny shapes (driver's pp validation)."""
    import optax

    from llm_consensus_tpu.models import get_config, init_params
    from llm_consensus_tpu.parallel.mesh import make_mesh
    from llm_consensus_tpu.train.loss import cross_entropy_loss

    devices = list(devices if devices is not None else jax.devices())[:n_devices]
    # Stage count = largest power of two ≤ n_devices that divides n_layers.
    cfg = get_config("tiny-llama", n_layers=8)
    pp = 1
    while pp * 2 <= min(n_devices, cfg.n_layers) and cfg.n_layers % (pp * 2) == 0:
        pp *= 2
    mesh = make_mesh({"pp": pp}, devices[:pp])
    microbatches = max(4, pp)  # v2 needs M % S == 0

    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size, jnp.int32
    )
    targets = jnp.roll(tokens, -1, axis=1)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def train_step(params, opt_state):
        def loss_fn(p):
            logits = pipeline_forward(
                p, cfg, tokens, mesh, microbatches=microbatches
            )
            return cross_entropy_loss(logits, targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    params, opt_state, loss = train_step(params, opt_state)
    loss = float(loss)
    assert jnp.isfinite(loss), "pipeline: non-finite loss"
    print(
        f"[dryrun] pipeline pp={pp} microbatches={microbatches} "
        f"loss={loss:.4f} ok"
    )
