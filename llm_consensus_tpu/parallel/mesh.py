"""Topology layer: carve `jax.devices()` into per-model mesh slices.

The reference's "topology" is a map from model name to HTTP endpoint
(/root/reference/cmd/llm-consensus/main.go:49-61). Here topology is
physical: a consensus run owns a set of TPU chips and must place N panel
models plus a judge on them. Each model gets its own `jax.sharding.Mesh`
over a disjoint device slice, so panel decode loops never contend for
chips and XLA collectives for one model ride only that model's slice of
the ICI fabric.

Axis conventions (used across parallel/, train/, and __graft_entry__):
  dp — data (batch) parallelism
  pp — pipeline stages (manual, via parallel.pipeline)
  sp — sequence parallelism: ring attention (parallel.ring) for engine
       prefill, activation sharding in the train step
  tp — tensor parallelism (GSPMD, via parallel.sharding); doubles as the
       expert axis for MoE unless a dedicated ``ep`` axis is present
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

from llm_consensus_tpu.models.config import ModelConfig
from llm_consensus_tpu.utils import knobs


def pvary(x, axis_name):
    """Mark ``x`` as device-varying over ``axis_name`` (str or tuple of
    names — under a multi-axis shard_map, carries must vary over every
    bound axis the data they combine with varies over).

    Compat shim: ``lax.pvary`` is deprecated in favor of ``lax.pcast``;
    older jax only has the former, and jax before the varying-manual-axes
    type system (< 0.5) has neither — there every shard_map input is
    already treated as varying, so the marker is correctly a no-op.
    """
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axis_name)
    return x


def make_mesh(
    axis_sizes: dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh with the given ``{axis_name: size}`` (insertion order).

    Sizes must multiply to ``len(devices)``; pass ``-1`` for at most one
    axis to infer its size (like numpy reshape).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axis_sizes)
    unknown = [a for a, s in sizes.items() if s == -1]
    if len(unknown) > 1:
        raise ValueError(f"at most one axis may be -1, got {unknown}")
    known = 1
    for a, s in sizes.items():
        if s != -1:
            known *= s
    if unknown:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[unknown[0]] = n // known
    total = 1
    for s in sizes.values():
        total *= s
    if total != n:
        raise ValueError(f"mesh {sizes} needs {total} devices, have {n}")
    import numpy as np

    dev_array = np.array(devices).reshape(tuple(sizes.values()))
    return Mesh(dev_array, tuple(sizes.keys()))


def carve_slices(
    devices: Sequence[jax.Device], sizes: Sequence[int]
) -> list[list[jax.Device]]:
    """Split ``devices`` into consecutive disjoint slices of ``sizes``.

    Consecutive device ids are physically adjacent on TPU slices, so each
    carved slice keeps its collectives on neighboring ICI links.
    """
    if sum(sizes) > len(devices):
        raise ValueError(
            f"requested {sum(sizes)} devices across slices, have {len(devices)}"
        )
    out, i = [], 0
    for s in sizes:
        if s <= 0:
            raise ValueError(f"slice size must be positive, got {s}")
        out.append(list(devices[i : i + s]))
        i += s
    return out


def best_tp(cfg: ModelConfig, n_devices: int) -> int:
    """Largest valid TP degree ≤ n_devices for ``cfg``.

    TP shards attention heads and the MLP hidden dim, so it must divide
    ``n_kv_heads`` (the binding constraint under GQA), ``n_heads`` and
    ``d_ff``. Falls back toward 1, which always works.
    """
    tp = 1
    d = 1
    while d <= n_devices:
        if (
            cfg.n_kv_heads % d == 0
            and cfg.n_heads % d == 0
            and cfg.d_ff % d == 0
            and n_devices % d == 0
        ):
            tp = d
        d *= 2
    return tp


@dataclass
class ModelPlacement:
    """One model pinned to a device slice with a concrete mesh.

    ``prefill_mesh`` is set only under disaggregated serving
    (:func:`split_roles`): ``mesh`` is then the DECODE role's sub-mesh
    (the resident continuous-batching pool) and ``prefill_mesh`` the
    disjoint slice the dedicated prefill workers run on.
    """

    model: str
    cfg: ModelConfig
    mesh: Mesh
    role: str  # "panel" | "judge"
    prefill_mesh: Optional[Mesh] = None

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size


@dataclass
class MeshPlan:
    """Placement of a whole consensus run onto the available chips."""

    placements: list[ModelPlacement] = field(default_factory=list)

    def for_model(self, model: str) -> Optional[ModelPlacement]:
        for p in self.placements:
            if p.model == model:
                return p
        return None


def host_groups(devices: Sequence[jax.Device]) -> list[list[jax.Device]]:
    """Group devices by host (``process_index``), hosts in index order.

    Single-process virtual meshes (tests, dry runs) yield one group.
    """
    by_proc: dict[int, list[jax.Device]] = {}
    for d in devices:
        by_proc.setdefault(getattr(d, "process_index", 0), []).append(d)
    return [by_proc[p] for p in sorted(by_proc)]


def _pow2_floor(x: int) -> int:
    p = 1
    while p * 2 <= x:
        p *= 2
    return p


def split_roles(
    cfg: ModelConfig,
    devices: Sequence[jax.Device],
    prefill_fraction: float = 0.5,
) -> tuple[Optional[Mesh], Mesh]:
    """Carve ONE preset's device slice into disjoint (prefill, decode)
    sub-meshes — the role-aware form of the per-model carving above,
    for disaggregated serving (``LLMC_DISAGG``): dedicated prefill
    workers on one sub-mesh hand finished prefix KV to the resident
    decode pool on the other, so admission prefill compute leaves the
    decode chips entirely.

    Both roles get power-of-two slices; the decode role keeps the
    LEADING devices (consecutive ids = adjacent ICI links, and the
    resident pool is the latency-critical half) and its own ``best_tp``,
    while the prefill role MATCHES the decode tp degree whenever its
    slice affords it: KV computed under a different tp degree carries a
    different float-reduction order, and matched degrees keep the
    handed-off bytes bitwise-identical to what the decode engine would
    have computed itself (the byte-identity contract's strong form). A
    prefill share too small to match falls back to its own ``best_tp``
    — the handoff still reshards correctly through the decode engine's
    shard_fn (engine/handoff.py), but low-bit drift between the roles'
    reduction orders is then possible, the same caveat as any placement
    change. A slice too small to split at all (< 2 devices) returns
    ``(None, decode_mesh)`` — the caller falls back to classic
    interleaved admission on the single mesh.
    """
    devices = list(devices)
    n = len(devices)
    if n < 2:
        tp = best_tp(cfg, n)
        return None, make_mesh({"dp": 1, "tp": tp}, devices[:tp])
    f = min(max(float(prefill_fraction), 0.05), 0.9)
    p = _pow2_floor(max(1, int(n * f)))
    if p >= n:
        p = _pow2_floor(n - 1)
    d = _pow2_floor(n - p)
    tp_d = best_tp(cfg, d)
    tp_p = tp_d if tp_d <= p else best_tp(cfg, p)
    decode_mesh = make_mesh({"dp": 1, "tp": tp_d}, devices[:tp_d])
    prefill_mesh = make_mesh(
        {"dp": 1, "tp": tp_p}, devices[n - p:n - p + tp_p]
    )
    return prefill_mesh, decode_mesh


def plan_panel(
    panel: Sequence[tuple[str, ModelConfig]],
    judge: Optional[tuple[str, ModelConfig]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    judge_fraction: float = 0.5,
    hosts: Optional[Sequence[Sequence[jax.Device]]] = None,
    disagg_fraction: Optional[float] = None,
) -> MeshPlan:
    """Place panel models + judge on disjoint slices of ``devices``.

    Policy (greedy, weight-proportional): the judge — typically the big
    TP-sharded model (BASELINE config[3]: 70B judge + 3×8B panel) — gets
    ``judge_fraction`` of the chips (rounded down to a power of two); the
    rest are split evenly across panel models. Every slice is a power-of-two
    so TP degrees stay MXU/ICI friendly. With fewer devices than models,
    slices are shared round-robin (time-multiplexed by the engine pool).

    **Host-aware placement** (the default whenever ``devices`` spans
    several processes, or an explicit ``hosts`` grouping): every model's
    slice stays WITHIN one host's ICI domain, because TP all-reduces
    activations every layer and would die on DCN latency. The judge
    takes the largest host; panel models round-robin over the other
    hosts, so panel decode loops run on different hosts' chips
    concurrently and DCN carries no per-layer traffic at all — the
    host-level fan-out is task parallelism, exactly like the reference's
    goroutines, just over hosts instead of HTTP connections (SURVEY.md
    §5). Execution matches ownership: each process drives only the
    engines whose slice it can address and results exchange host-side
    (parallel/multicontroller.py, runner/multihost.py).
    ``LLMC_MULTIHOST_PLACEMENT=0`` forces the old single-domain planning
    (debugging only — a cross-host TP mesh is a per-layer DCN all-reduce).
    """
    devices = list(devices if devices is not None else jax.devices())
    if not panel and judge is None:
        return MeshPlan()
    if hosts is not None:
        groups = [list(g) for g in hosts]
        devices = [d for g in groups for d in g]
    elif knobs.get_bool("LLMC_MULTIHOST_PLACEMENT"):
        groups = host_groups(devices)  # single-process: one group
    else:
        groups = [devices]
    if len(groups) > 1:
        return _plan_multihost(
            panel, judge, groups, judge_fraction,
            disagg_fraction=disagg_fraction,
        )

    def placed(name: str, cfg: ModelConfig, slice_devs, role: str):
        """One placement over its device slice — split into prefill and
        decode sub-meshes under disaggregation, one mesh otherwise."""
        if disagg_fraction is not None and len(slice_devs) >= 2:
            pmesh, dmesh = split_roles(cfg, slice_devs, disagg_fraction)
            return ModelPlacement(name, cfg, dmesh, role, prefill_mesh=pmesh)
        tp = best_tp(cfg, len(slice_devs))
        return ModelPlacement(
            name, cfg, make_mesh({"dp": 1, "tp": tp}, slice_devs[:tp]), role
        )

    n = len(devices)
    pow2_floor = _pow2_floor
    plan = MeshPlan()
    remaining = devices
    if judge is not None and n >= 2:
        j = pow2_floor(max(1, int(n * judge_fraction)))
        judge_devs, remaining = remaining[n - j :], remaining[: n - j]
    elif judge is not None:
        judge_devs = devices  # single chip: judge shares it
    else:
        judge_devs = []

    if panel:
        per = max(1, pow2_floor(len(remaining) // len(panel))) if remaining else 1
        pool = remaining if remaining else devices
        taken: set = set()
        for i, (name, cfg) in enumerate(panel):
            start = (i * per) % max(1, len(pool))
            devs = pool[start : start + per]
            if len(devs) < per:  # wrap: share the pool round-robin
                devs = (pool + pool)[start : start + per]
            p = placed(name, cfg, devs, "panel")
            used = [
                d for m in (p.prefill_mesh, p.mesh) if m is not None
                for d in m.devices.flat
            ]
            if taken & {d.id for d in used}:
                _warn_wrap_sharing(name, used)
            taken |= {d.id for d in used}
            plan.placements.append(p)

    if judge is not None:
        name, cfg = judge
        plan.placements.append(placed(name, cfg, judge_devs, "judge"))
    return plan


def _warn_wrap_sharing(name: str, devs: Sequence[jax.Device]) -> None:
    """Models outnumber chips: slices time-multiplex. Decode loops on a
    shared slice contend for the chip (the engine pool serializes
    dispatches, so it is correct but slower) — say so instead of letting
    a silently shared placement read as a perf mystery."""
    import warnings

    warnings.warn(
        f"model {name!r} shares chips {sorted(d.id for d in devs)} with "
        "another placement (more models than devices): decode loops will "
        "time-multiplex the slice",
        RuntimeWarning,
        stacklevel=3,
    )


def _plan_multihost(
    panel: Sequence[tuple[str, ModelConfig]],
    judge: Optional[tuple[str, ModelConfig]],
    groups: list[list[jax.Device]],
    judge_fraction: float = 0.5,
    disagg_fraction: Optional[float] = None,
) -> MeshPlan:
    """Host-aware placement, weight-proportional: one ICI domain per
    model slice (see plan_panel's policy note), with hosts and chips
    allotted by PARAMETER COUNT — the biggest model gets the biggest
    host regardless of role (a 70B panel member outranks an 8B judge;
    round 2 always handed the judge the largest host). ``judge_fraction``
    scales the judge's weight (0.5 = neutral, its real size; higher
    biases chips toward the judge the way the single-domain planner's
    fraction does).
    """
    plan = MeshPlan()
    hosts = sorted(groups, key=len, reverse=True)
    jf = min(max(judge_fraction, 0.01), 0.99)
    items: list[tuple[str, ModelConfig, str, float]] = [
        (name, cfg, "panel", float(max(1, cfg.n_params(active_only=True))))
        for name, cfg in panel
    ]
    if judge is not None:
        name, cfg = judge
        items.append((
            name, cfg, "judge",
            float(max(1, cfg.n_params(active_only=True))) * (jf / (1.0 - jf)),
        ))
    # Heaviest model first onto the host where it keeps weight-per-chip
    # lowest — so the biggest model lands on the biggest (least loaded)
    # host and co-tenants balance by size, not by count.
    items.sort(key=lambda it: -it[3])
    loads = [0.0] * len(hosts)
    assigned: list[list[tuple[str, ModelConfig, str, float]]] = [
        [] for _ in hosts
    ]
    for it in items:
        h = min(
            range(len(hosts)),
            key=lambda i: ((loads[i] + it[3]) / len(hosts[i]), i),
        )
        assigned[h].append(it)
        loads[h] += it[3]

    for host, its in zip(hosts, assigned):
        if not its:
            continue
        total = sum(w for *_, w in its)
        start = 0
        for name, cfg, role, w in its:
            # Weight-proportional power-of-two share of this host's chips.
            per = min(
                len(host), max(1, _pow2_floor(int(len(host) * w / total)))
            )
            devs = host[start : start + per]
            if len(devs) < per:  # wrap: share the host round-robin
                devs = (host + host)[start % len(host):][:per]
                _warn_wrap_sharing(name, devs)
            start += per
            if disagg_fraction is not None and len(devs) >= 2:
                # Role split stays WITHIN the host's ICI domain: the KV
                # handoff is a bulk block copy, but the prefill engine's
                # own TP collectives must not cross DCN.
                pmesh, dmesh = split_roles(cfg, devs, disagg_fraction)
                plan.placements.append(
                    ModelPlacement(name, cfg, dmesh, role, prefill_mesh=pmesh)
                )
            else:
                tp = best_tp(cfg, len(devs))
                mesh = make_mesh({"dp": 1, "tp": tp}, devs[:tp])
                plan.placements.append(ModelPlacement(name, cfg, mesh, role))
    return plan
