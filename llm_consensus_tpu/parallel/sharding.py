"""GSPMD sharding specs for model params and KV caches.

Megatron-style tensor parallelism expressed as `PartitionSpec` trees that
mirror ``models.transformer.init_params`` exactly: QKV projections are
column-parallel (heads sharded over ``tp``), the output projection is
row-parallel, the MLP shards its hidden dim, and MoE experts shard over the
expert axis (``ep`` if the mesh has one, else ``tp``). XLA/GSPMD inserts
the (all-reduce after row-parallel matmuls, all-to-alls at MoE dispatch)
collectives — this module only declares placements; there are no explicit
collectives on this path.

The reference has no analog (its compute is three HTTP clients —
/root/reference/internal/provider/{openai,anthropic,google}.go); this is
what "a model bigger than one chip" requires instead.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_consensus_tpu.models.config import ModelConfig


def _axis(mesh: Optional[Mesh], name: str, dim: int) -> Optional[str]:
    """Use mesh axis ``name`` for a tensor dim only if valid & divisible."""
    if mesh is None or name not in mesh.axis_names:
        return None
    size = mesh.shape[name]
    if size == 1 or dim % size != 0:
        return None
    return name


def param_specs(cfg: ModelConfig, mesh: Optional[Mesh] = None) -> dict:
    """PartitionSpec pytree matching ``init_params(cfg)``.

    ``mesh=None`` returns the canonical (unsanitized) specs; with a mesh,
    any dim not divisible by its axis size degrades to replicated so the
    same code serves tp=1 (single chip) through tp=16 without special
    cases.
    """
    dh = cfg.head_dim
    tp_q = _axis(mesh, "tp", cfg.n_heads * dh)
    tp_kv = _axis(mesh, "tp", cfg.n_kv_heads * dh)
    tp_ff = _axis(mesh, "tp", cfg.d_ff)
    tp_vocab = _axis(mesh, "tp", cfg.vocab_size)
    if mesh is not None and all(
        dict(mesh.shape).get(a, 1) > 1 for a in ("dp", "tp", "sp")
    ):
        # jax 0.4.x GSPMD miscompiles the fwd+bwd train step on 3-axis
        # dp×tp×sp meshes when the embedding table is vocab-sharded over
        # tp: the loss computed inside value_and_grad diverges from the
        # identical forward-only program by ~2e-3 RELATIVE in fp32 (not
        # reassociation ulps — the forward alone matches to 1e-7, and
        # every 2-axis sub-mesh of the same factors is exact). Bisected
        # to the embed/lm_head specs: replicating either the vocab
        # sharding or the attention projections restores exactness, and
        # replicating the (small) vocab table is the cheap one. Same
        # failure class as the non-dividing-tp qkv pin in
        # models/transformer.py — a version-scoped workaround, keyed on
        # exactly the miscompiling mesh shape so inference meshes
        # (tp-only, tp×sp, dp×tp) keep the sharded LM head.
        tp_vocab = None
    layers: dict = {
        "attn_norm": P(None, None),
        "mlp_norm": P(None, None),
        "wq": P(None, None, tp_q),
        "wk": P(None, None, tp_kv),
        "wv": P(None, None, tp_kv),
        "wo": P(None, tp_q, None),
    }
    if cfg.qkv_bias:
        layers["bq"] = P(None, tp_q)
        layers["bk"] = P(None, tp_kv)
        layers["bv"] = P(None, tp_kv)
    if cfg.is_moe:
        ep_name = "ep" if (mesh is None or "ep" in mesh.axis_names) else "tp"
        ep = _axis(mesh, ep_name, cfg.n_experts)
        layers["w_router"] = P(None, None, None)
        # Experts shard over ep; each expert's hidden dim additionally
        # shards over tp when both axes exist (ep×tp 2-D sharding).
        inner = tp_ff if ep != "tp" else None
        layers["w_gate"] = P(None, ep, None, inner)
        layers["w_up"] = P(None, ep, None, inner)
        layers["w_down"] = P(None, ep, inner, None)
    else:
        layers["w_gate"] = P(None, None, tp_ff)
        layers["w_up"] = P(None, None, tp_ff)
        layers["w_down"] = P(None, tp_ff, None)
    specs = {
        "embed": P(tp_vocab, None),
        "final_norm": P(None),
        "layers": layers,
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, tp_vocab)
    return specs


def opt_moment_specs(cfg: ModelConfig, mesh: Optional[Mesh] = None) -> dict:
    """Cross-replica specs for optimizer moment buffers (ZeRO-1-style).

    Each AdamW moment mirrors its param's tensor-parallel spec, then its
    first still-replicated dim that ``dp`` divides additionally shards
    over ``dp`` — the weight-update state partitions across data-parallel
    replicas instead of being mirrored into every one (the automatic
    cross-replica-sharding scheme: moments are 2/3 of AdamW state, so at
    dp=8 this drops that slice's residency ~8×; GSPMD inserts the
    reduce-scatter/all-gather pair around the update). Wherever no dim
    divides, the moment stays on the plain param spec — same degradation
    contract as :func:`param_specs`.
    """
    from llm_consensus_tpu.models import init_params

    shapes = jax.eval_shape(lambda: init_params(cfg, jax.random.PRNGKey(0)))
    specs = param_specs(cfg, mesh)
    dp = (
        mesh.shape["dp"]
        if mesh is not None and "dp" in mesh.axis_names
        and mesh.shape["dp"] > 1 else None
    )

    def widen(leaf, spec):
        if dp is None:
            return spec
        entries = list(spec) + [None] * (len(leaf.shape) - len(spec))
        for i, ax in enumerate(entries):
            if ax is None and leaf.shape[i] % dp == 0:
                entries[i] = "dp"
                return P(*entries)
        return spec

    return jax.tree.map(widen, shapes, specs)


def cache_specs(cfg: ModelConfig, mesh: Optional[Mesh] = None, batch: int = 1) -> dict:
    """PartitionSpec pytree matching ``init_kv_cache``: [L, B, S, Hkv, dh].

    KV heads shard with the attention TP split; batch shards over dp when
    it divides (decode streams are batch=1, so dp stays replicated there).
    """
    tp_kv = _axis(mesh, "tp", cfg.n_kv_heads)
    dp = _axis(mesh, "dp", batch)
    spec = P(None, dp, None, tp_kv, None)
    return {"k": spec, "v": spec}


def abstract_param_bytes(cfg: ModelConfig, mesh: Mesh) -> tuple[int, int]:
    """(total_bytes, tp_sharded_bytes) of ``cfg``'s parameter tree on
    ``mesh`` — shapes and specs only, nothing materialized.

    The placement-feasibility primitive for big models: a 70B judge's
    residency math (does it fit at tp=8? at int8?) must be answerable
    without 140 GB of HBM. Also validates that every sharded spec is
    constructible on the mesh.
    """
    import jax

    from llm_consensus_tpu.models import init_params

    shapes = jax.eval_shape(
        lambda: init_params(cfg, jax.random.PRNGKey(0))
    )
    specs = param_specs(cfg, mesh)
    acc = {"total": 0, "sharded": 0}

    def tally(leaf, spec):
        nbytes = leaf.size * leaf.dtype.itemsize
        acc["total"] += nbytes
        if any(ax is not None for ax in spec):
            NamedSharding(mesh, spec)  # constructible on this mesh
            acc["sharded"] += nbytes

    # tree.map (not a leaves zip): a param present in init_params but
    # missing from param_specs — or vice versa — must error loudly, not
    # silently misalign the byte accounting.
    jax.tree.map(tally, shapes, specs)
    return acc["total"], acc["sharded"]


def shard_pytree(tree, specs, mesh: Mesh):
    """Place ``tree`` on ``mesh`` according to a matching spec pytree."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
    )


def make_shard_fn(cfg: ModelConfig, mesh: Mesh) -> Callable:
    """Shard fn for ``engine.Engine(shard_fn=...)``.

    Dispatches on pytree shape: the params tree (has ``embed``) gets
    ``param_specs``, the KV cache (has ``k``/``v``) gets ``cache_specs``.
    """

    def shard(tree):
        if isinstance(tree, dict) and "embed" in tree:
            return shard_pytree(tree, param_specs(cfg, mesh), mesh)
        if isinstance(tree, dict) and set(tree) == {"k", "v"}:
            # int8 caches nest {"q8", "s"} under k/v: codes keep the
            # [L, B, S, Hkv, dh] layout; scales are seq-minor
            # [L, B, Hkv, S] (heads on axis 2), so their tp split moves
            # with the head axis. Layout discrimination routes through
            # ops.quant.kv_seq_axis, the rule's single owner.
            from llm_consensus_tpu.ops.quant import kv_seq_axis

            k_spec = cache_specs(cfg, mesh)["k"]
            s_spec = P(k_spec[0], k_spec[1], k_spec[3], k_spec[2])
            return shard_pytree(
                tree,
                jax.tree.map(
                    lambda leaf: (
                        k_spec if kv_seq_axis(leaf) == 2 else s_spec
                    ),
                    tree,
                ),
                mesh,
            )
        raise ValueError(f"unrecognized pytree with keys {list(tree)}")

    return shard
