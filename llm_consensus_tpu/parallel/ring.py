"""Ring attention: sequence/context parallelism over an ICI ring.

Long-context path for the judge: a consensus judge prompt concatenates the
user prompt plus every panel answer (consensus/judge.py, mirroring the
reference template at /root/reference/internal/consensus/judge.go:21-25),
so judge prefill length grows with panel size — past a single chip's HBM,
the sequence dimension itself must shard.

Design (Ring Attention, Liu et al. 2023 — re-derived for shard_map):
  * Q, K, V shard over mesh axis ``axis_name`` on the sequence dim. Each
    device keeps its Q block resident and circulates K/V blocks around the
    ring with ``ppermute`` — every device sees every KV block after
    ``axis_size`` hops, so peak memory is O(S/n) while the math equals
    full attention.
  * Blocks combine with the online-softmax recurrence (running row max
    ``m``, normalizer ``l``, unnormalized accumulator ``out`` — fp32),
    the same update flash attention uses across KV tiles; a block is just
    a very large tile that happens to live on another chip.
  * Causality rides on absolute positions: each KV block carries its
    position vector around the ring, so masking needs no step/rank
    arithmetic and sliding windows compose for free.
  * ``lax.scan`` drives the hops: XLA sees a static ring of
    collective-permutes and overlaps each hop's transfer with the current
    block's matmuls on the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_consensus_tpu.utils.jaxcompat import shard_map as _shard_map
from llm_consensus_tpu.ops.attention import NEG_INF
from llm_consensus_tpu.parallel.mesh import pvary


def _block_attention(
    q: jax.Array,        # [B, T, Hkv, G, dh]  (GQA-grouped queries)
    k: jax.Array,        # [B, S, Hkv, dh]
    v: jax.Array,        # [B, S, Hkv, dh]
    mask: jax.Array,     # [B, T, S] bool
    scale: float,
    logit_softcap: Optional[float],
) -> tuple[jax.Array, jax.Array]:
    """One KV block's (scores-max, exp-weighted sums) for online softmax."""
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", q, k, preferred_element_type=jnp.float32
    ) * scale
    if logit_softcap is not None:
        # Gemma-family softcap; applied pre-mask exactly as ops.attention.
        scores = logit_softcap * jnp.tanh(scores / logit_softcap)
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    block_max = jnp.max(scores, axis=-1)                       # [B,Hkv,G,T]
    p = jnp.exp(scores - block_max[..., None])
    p = jnp.where(mask[:, None, None, :, :], p, 0.0)
    block_sum = jnp.sum(p, axis=-1)                            # [B,Hkv,G,T]
    block_out = jnp.einsum("bkgts,bskd->btkgd", p.astype(v.dtype), v)
    return block_max, (block_sum, block_out)


def _ring_attention_local(
    q: jax.Array,          # [B, Tl, Hq, dh] local query shard
    k: jax.Array,          # [B, Tl, Hkv, dh] local KV shard
    v: jax.Array,
    axis_name: str,
    scale: float,
    sliding_window: Optional[int],
    logit_softcap: Optional[float],
    vary_axes: tuple = (),  # every shard_map axis the inputs vary over
) -> jax.Array:
    """Per-device body (runs under shard_map over ``axis_name``)."""
    axis_size = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, tl, hq, dh = q.shape
    hkv = k.shape[2]
    g = hq // hkv

    local_pos = jnp.arange(tl, dtype=jnp.int32)
    q_pos = jnp.broadcast_to((idx * tl + local_pos)[None, :], (b, tl))
    kv_pos0 = q_pos

    qg = q.reshape(b, tl, hkv, g, dh)
    # Ring: device i sends its current KV block to i+1, receives from i-1.
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def hop(carry, _):
        k_blk, v_blk, kv_pos, out, m, l = carry
        causal = kv_pos[:, None, :] <= q_pos[:, :, None]
        if sliding_window is not None:
            causal &= kv_pos[:, None, :] > (q_pos[:, :, None] - sliding_window)
        blk_max, (blk_sum, blk_out) = _block_attention(
            qg, k_blk, v_blk, causal, scale, logit_softcap
        )
        m_new = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - m_new)
        blk_corr = jnp.exp(blk_max - m_new)
        l_new = l * corr + blk_sum * blk_corr
        # out layout [B,T,Hkv,G,dh]; factors come in [B,Hkv,G,T]
        corr_t = jnp.moveaxis(corr, -1, 1)[..., None]
        blk_corr_t = jnp.moveaxis(blk_corr, -1, 1)[..., None]
        out_new = out * corr_t + blk_out.astype(jnp.float32) * blk_corr_t
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        kv_pos = jax.lax.ppermute(kv_pos, axis_name, perm)
        return (k_blk, v_blk, kv_pos, out_new, m_new, l_new), None

    # pvary: mark the accumulator inits as device-varying over every bound
    # axis so the scan carry types match (they combine with varying data —
    # the ring axis always, plus the head axis when heads are sharded).
    axes = tuple(vary_axes) or (axis_name,)
    out0 = pvary(jnp.zeros((b, tl, hkv, g, dh), jnp.float32), axes)
    m0 = pvary(jnp.full((b, hkv, g, tl), NEG_INF, jnp.float32), axes)
    l0 = pvary(jnp.zeros((b, hkv, g, tl), jnp.float32), axes)
    (_, _, _, out, _, l), _ = jax.lax.scan(
        hop, (k, v, kv_pos0, out0, m0, l0), None, length=axis_size
    )
    l_t = jnp.moveaxis(l, -1, 1)[..., None]                    # [B,T,Hkv,G,1]
    out = out / jnp.maximum(l_t, 1e-30)
    return out.reshape(b, tl, hq, dh).astype(q.dtype)


def ring_attention(
    q: jax.Array,          # [B, S, Hq, dh] (sequence-sharded over axis_name)
    k: jax.Array,          # [B, S, Hkv, dh]
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    logit_softcap: Optional[float] = None,
    head_axis: Optional[str] = None,
) -> jax.Array:
    """Causal GQA attention with the sequence dim sharded over ``axis_name``.

    Equals ``ops.attention`` with a causal mask, computed without any
    device ever holding the full sequence. S must divide evenly by the
    axis size (pad prompts to the shard multiple — static shapes anyway).

    ``head_axis`` additionally shards the head dim (TP): rings then run
    per head-shard — attention is per-head, so the two compositions never
    communicate, and SP×TP meshes work with one shard_map. The local body
    sees per-shard head counts, so GQA grouping requires the head axis to
    divide both Hq and Hkv.
    """
    if q.shape[1] % mesh.shape[axis_name] != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by "
            f"{axis_name}={mesh.shape[axis_name]}"
        )
    if head_axis is not None:
        h = mesh.shape[head_axis]
        if q.shape[2] % h or k.shape[2] % h:
            raise ValueError(
                f"head counts {q.shape[2]}/{k.shape[2]} not divisible by "
                f"{head_axis}={h}"
            )
    scale = q.shape[-1] ** -0.5 if scale is None else scale
    seq_spec = P(None, axis_name, head_axis, None)
    vary_axes = (axis_name,) if head_axis is None else (axis_name, head_axis)
    fn = _shard_map(
        partial(
            _ring_attention_local,
            axis_name=axis_name,
            scale=scale,
            sliding_window=sliding_window,
            logit_softcap=logit_softcap,
            vary_axes=vary_axes,
        ),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
    )
    return fn(q, k, v)
