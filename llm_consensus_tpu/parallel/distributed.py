"""Multi-host distribution: process init + hybrid DCN×ICI meshes.

The reference's "distributed backend" is HTTPS to three vendors
(SURVEY.md §5); scaling here means more TPU hosts. Two pieces:

  * :func:`initialize` — idempotent wrapper over
    ``jax.distributed.initialize``. On Cloud TPU pods the coordinator is
    auto-detected; elsewhere it comes from ``LLMC_COORDINATOR`` /
    ``LLMC_NUM_PROCESSES`` / ``LLMC_PROCESS_ID`` or explicit arguments.
    Single-process runs are a no-op, so the CLI can call it
    unconditionally.
  * :func:`hybrid_mesh` — a mesh whose *outer* axes cross hosts (traffic
    rides DCN: data parallelism, rarely pipeline) and whose *inner* axes
    stay within a host's ICI domain (tensor/sequence/expert parallelism,
    which all-reduce activations every layer and would die on DCN
    latency). Axis names are the framework's standard dp/pp/tp/sp/ep, so
    ``parallel.sharding`` / ``train`` consume the result unchanged — the
    scaling-book recipe: pick the mesh, annotate shardings, let XLA place
    the collectives on the right fabric.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh
from llm_consensus_tpu.utils import knobs


def is_initialized() -> bool:
    """True once ``jax.distributed.initialize`` has run in this process."""
    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    from jax._src import distributed  # older jax: no public predicate

    return distributed.global_state.client is not None


def _pod_env() -> bool:
    """True in a multi-host TPU pod environment where
    ``jax.distributed.initialize()`` can auto-detect every argument.

    ``TPU_WORKER_HOSTNAMES`` counts only with >1 host — single-host images
    (and the axon relay) set it to one hostname, and auto-init after the
    backend exists raises.
    """
    if knobs.get_bool("LLMC_DISTRIBUTED"):
        return True
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS") or os.environ.get(
        "CLOUD_TPU_CLUSTER_COORDINATOR_ADDRESS"
    ):
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) > 1


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join (or skip joining) the multi-host cluster; returns True if joined.

    Resolution order: explicit args > ``LLMC_COORDINATOR`` /
    ``LLMC_NUM_PROCESSES`` / ``LLMC_PROCESS_ID`` env > full auto-detection
    when a TPU-pod environment is present (``MEGASCALE_*``/``TPU_WORKER_*``
    markers, or ``LLMC_DISTRIBUTED=1`` to force the attempt). With no
    configuration and no pod markers, this is a no-op so single-host runs
    never block on a coordinator. Must run before the JAX backend
    initializes (before the first ``jax.devices()``/trace/computation).
    """
    if is_initialized():
        return True
    coordinator_address = (
        coordinator_address or knobs.get_str("LLMC_COORDINATOR") or None
    )
    env_n = knobs.raw("LLMC_NUM_PROCESSES")
    env_id = knobs.raw("LLMC_PROCESS_ID")
    if num_processes is None and env_n:
        num_processes = int(env_n)
    if process_id is None and env_id:
        process_id = int(env_id)
    if coordinator_address is None and num_processes is None:
        if not _pod_env():
            return False  # single-host: nothing to join
        jax.distributed.initialize()  # pod: every argument auto-detects
        return True
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def hybrid_mesh(
    dcn_axes: dict[str, int],
    ici_axes: dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh with ``dcn_axes`` crossing hosts and ``ici_axes`` within them.

    The DCN axes (outer, slowest-varying) partition devices into
    contiguous per-host granules; ICI axes order within a granule. Granule
    membership comes from each device's ``process_index`` when the
    processes differ (real multi-host), else from contiguous equal splits
    (single-process virtual meshes — tests, the driver's dry run).

    Every collective a sharding induces along an ICI axis then stays
    inside one host's ICI domain; only DCN-axis collectives (e.g. the
    per-step gradient all-reduce over ``dp``) cross hosts.
    """
    devices = list(devices if devices is not None else jax.devices())
    n_granules = 1
    for s in dcn_axes.values():
        n_granules *= s
    per_granule = 1
    for s in ici_axes.values():
        per_granule *= s
    if n_granules * per_granule != len(devices):
        raise ValueError(
            f"mesh {dcn_axes}×{ici_axes} needs {n_granules * per_granule} "
            f"devices, have {len(devices)}"
        )

    from llm_consensus_tpu.parallel.mesh import host_groups

    grouped = host_groups(devices)
    if len(grouped) > 1:
        granules = grouped
        if len(granules) != n_granules or any(
            len(g) != per_granule for g in granules
        ):
            raise ValueError(
                f"DCN axes {dcn_axes} want {n_granules} granules of "
                f"{per_granule}; processes provide "
                f"{[len(g) for g in granules]}"
            )
    else:
        granules = [
            devices[i * per_granule : (i + 1) * per_granule]
            for i in range(n_granules)
        ]

    shape = tuple(dcn_axes.values()) + tuple(ici_axes.values())
    dev_array = np.array(granules).reshape(shape)
    return Mesh(dev_array, tuple(dcn_axes.keys()) + tuple(ici_axes.keys()))
