"""Multi-controller execution: per-process engine ownership + host-side
result exchange.

In a multi-host JAX deployment every process runs the same program, but a
process can only *address* its own host's chips. This framework places
each model's mesh inside ONE host's ICI domain (parallel/mesh.py
host-aware planning), so each model has a unique owner process: the
owner builds and drives the engine; everyone else receives the results
host-side. The phases line up with the consensus run's natural barriers:

  * **Panel fan-out**: each process runs the best-effort runner over the
    models it owns (its own threads, its own chips — the reference's
    goroutine fan-out, /root/reference/internal/runner/runner.go:60-115,
    lifted to processes), then all processes exchange serialized
    responses with one allgather. Every process ends the phase with the
    identical merged RunResult, so all downstream control flow (judge
    prompt, rounds, voting) stays deterministic across controllers.
  * **Judge synthesis**: the judge's owner runs the real query; the text
    broadcasts to the rest. Streaming callbacks fire with real chunks on
    the owner and once with the full text elsewhere (the ProviderFunc
    contract, /root/reference/internal/provider/provider.go:39-55).

The exchange primitives ride jax collectives over DCN
(``multihost_utils``), so there is no second transport to configure —
the cluster that serves the models also carries their results. In a
single-process run every primitive short-circuits to the identity, which
is what lets the driver's dry run and the unit tests exercise the full
multi-controller code path without real processes.

The reference has no analog: its "hosts" are three vendor HTTP endpoints
(SURVEY.md §5 "distributed communication backend").
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import asdict
from typing import Callable, Optional

import numpy as np

from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.providers.base import (
    Provider, Request, Response, StreamCallback)
from llm_consensus_tpu.utils.context import Context
from llm_consensus_tpu.utils import knobs


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_multicontroller() -> bool:
    """True when several controller processes share this cluster."""
    return process_count() > 1


def mesh_owner(mesh) -> int:
    """The process that drives engines on ``mesh``.

    Host-aware planning keeps every model's slice within one host, so the
    minimum ``process_index`` over the mesh's devices IS that host; for a
    mis-planned mesh spanning hosts the minimum is still deterministic
    and identical on every process, which is all the exchange needs.
    """
    return min(
        getattr(d, "process_index", 0) for d in mesh.devices.flat
    )


def model_owner(registry, model: str) -> int:
    """Owner process for ``model``: its placement's host for on-device
    models, process 0 for everything else (HTTP providers run anywhere;
    one process must own them so they are queried exactly once)."""
    try:
        provider = registry.get(model)
    except Exception:
        return 0  # unknown model: process 0 reports the failure
    placement = getattr(provider, "placement", None)
    if placement is None:
        return 0
    try:
        mesh = placement(model)
    except Exception:
        return 0
    return 0 if mesh is None else mesh_owner(mesh)


# -- byte-level collectives ---------------------------------------------------


def allgather_bytes(payload: bytes) -> list[bytes]:
    """Every process's ``payload``, in process order.

    Variable lengths are handled with a length allgather first, then a
    padded payload allgather; single-process short-circuits.
    """
    if not is_multicontroller():
        return [payload]
    from jax.experimental import multihost_utils

    length = np.asarray(len(payload), np.int32)
    lengths = np.asarray(
        multihost_utils.process_allgather(length)
    ).reshape(-1)
    width = int(lengths.max()) if lengths.size else 0
    buf = np.zeros((max(width, 1),), np.uint8)
    data = np.frombuffer(payload, np.uint8)
    buf[: data.size] = data
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    return [
        gathered[i, : int(lengths[i])].tobytes()
        for i in range(len(lengths))
    ]


def broadcast_bytes(payload: Optional[bytes], owner: int) -> bytes:
    """``payload`` from process ``owner`` to everyone (None elsewhere)."""
    if not is_multicontroller():
        assert payload is not None
        return payload
    from jax.experimental import multihost_utils

    me = process_index()
    is_source = me == owner
    length = np.asarray(len(payload) if is_source else 0, np.int32)
    length = int(
        np.asarray(
            multihost_utils.broadcast_one_to_all(length, is_source=is_source)
        )
    )
    buf = np.zeros((max(length, 1),), np.uint8)
    if is_source:
        buf[:length] = np.frombuffer(payload, np.uint8)
    out = np.asarray(
        multihost_utils.broadcast_one_to_all(buf, is_source=is_source)
    )
    return out[:length].tobytes()


def allgather_json(obj) -> list:
    return [
        json.loads(p.decode("utf-8"))
        for p in allgather_bytes(json.dumps(obj).encode("utf-8"))
    ]


# -- degraded mode ------------------------------------------------------------
#
# GSPMD-style collectives make a dead peer a total outage: one controller
# that never reaches the allgather stalls every other forever. The bounded
# variants below turn that into a partial outage — wait up to a deadline,
# then merge what arrived and remember the peers that didn't, so the run's
# best-effort contract ("only a total wipeout is an error", runner.go:122)
# survives a host death.

DEFAULT_ALLGATHER_TIMEOUT_S = 60.0

_degraded_lock = sanitizer.make_lock("parallel.degraded")
_degraded: set[int] = set()


def mark_degraded(peers) -> None:
    """Record controller processes that missed a collective deadline."""
    peers = [int(p) for p in peers]
    with _degraded_lock:
        new = [p for p in peers if p not in _degraded]
        _degraded.update(peers)
    if new:
        # Degraded-mode transition on the run timeline: the instant the
        # run stopped trusting these peers (obs/; no-op when disabled).
        from llm_consensus_tpu import obs

        r = obs.recorder()
        if r is not None:
            r.instant("degraded", tid="mc", peers=sorted(new))


def degraded_peers() -> frozenset:
    """Controllers known to have dropped out of this run's collectives."""
    with _degraded_lock:
        return frozenset(_degraded)


def reset_degraded() -> None:
    """Forget dropped peers (tests / a fresh run on a healed cluster)."""
    with _degraded_lock:
        _degraded.clear()


def allgather_timeout(ctx: Optional[Context] = None) -> float:
    """Deadline for one bounded allgather: the run context's remaining
    budget when it has one, capped by ``LLMC_ALLGATHER_TIMEOUT`` (default
    60 s) — a run with no deadline must still never hang on a dead peer."""
    cap = knobs.get_float(
        "LLMC_ALLGATHER_TIMEOUT", DEFAULT_ALLGATHER_TIMEOUT_S
    )
    rem = ctx.remaining() if ctx is not None else None
    return cap if rem is None else min(cap, rem)


def _simulated_allgather(fs, payload: bytes, timeout: Optional[float]):
    """Apply a controller_drop / controller_late fault to one gather.

    Simulates the peer topology the fault names (``host=H`` implies at
    least H+1 controllers) so single-process tests and the chaos dryrun
    exercise the degraded merge without real processes. A late peer whose
    delay fits the deadline behaves as a normal full gather; one whose
    delay exceeds it is dropped exactly like a dead peer.
    """
    me = process_index()
    host = int(fs.param("host", 1))
    if fs.kind == "controller_late":
        delay = float(fs.param("s", 0.05))
        if timeout is None or delay <= timeout:
            time.sleep(delay)
            return allgather_bytes(payload), []
        time.sleep(timeout)
    n = max(process_count(), host + 1, me + 1)
    # Same semantics as the real timeout path below: once a gather times
    # out, every non-local peer's payload (and liveness) is unknown, so
    # missing and the degraded set cover them all — not just the fault's
    # named host. Keeping the two sets aligned means the merge never
    # books a peer's models failed while later exchanges still treat that
    # peer as healthy.
    missing = [i for i in range(n) if i != me]
    mark_degraded(missing)
    return [payload if i == me else None for i in range(n)], missing


def allgather_bytes_bounded(
    payload: bytes, timeout: Optional[float] = None
) -> "tuple[list[Optional[bytes]], list[int]]":
    """Every reachable process's payload, plus who missed the deadline.

    Returns ``(parts, missing)``: ``parts[i]`` is process i's payload or
    None when i never arrived; ``missing`` lists the absent indices. The
    underlying collective is all-or-nothing, so a timeout surrenders every
    remote payload at once — the callers' merge semantics (book the absent
    owners' models as failed, keep the survivors) treat that as the
    partial outage it is. Timed-out peers land in the module's degraded
    set so later broadcasts can route around them.
    """
    import time as _time

    from llm_consensus_tpu import obs
    from llm_consensus_tpu.obs.attrib import tag as _attrib_tag

    r = obs.recorder()
    led = obs.attrib.ledger()
    if r is None and led is None:
        return _allgather_bytes_bounded(payload, timeout)
    t0 = r.now() if r is not None else 0
    t0_wall = _time.monotonic()
    with _attrib_tag("allgather"):
        parts, missing = _allgather_bytes_bounded(payload, timeout)
    # The exchange wall — including the full bounded wait when a peer is
    # dead — is the span a degraded run's timeline must show.
    if r is not None:
        r.complete(
            "allgather", t0, tid="mc", bytes=len(payload),
            peers=len(parts), missing=list(missing),
            timeout_s=timeout,
        )
    if led is not None:
        # Chip-time attribution: the exchange blocks this controller's
        # pipeline end to end, so its wall is device-unavailable time.
        led.observe_device("allgather", _time.monotonic() - t0_wall)
    return parts, missing


def _allgather_bytes_bounded(
    payload: bytes, timeout: Optional[float] = None
) -> "tuple[list[Optional[bytes]], list[int]]":
    from llm_consensus_tpu import faults

    fault_plan = faults.plan()
    if fault_plan is not None:
        fs = fault_plan.fire("allgather")
        if fs is not None:
            return _simulated_allgather(fs, payload, timeout)
    if not is_multicontroller():
        return [payload], []
    already = degraded_peers()
    if already:
        # Collective lockstep was already lost this run (a prior timeout;
        # peer liveness is unknowable from here). Entering another
        # collective would just pay the full deadline again — or hang a
        # peer that DID arrive last time — so the exchange goes straight
        # to local-only.
        me, n = process_index(), process_count()
        return (
            [payload if i == me else None for i in range(n)],
            [i for i in range(n) if i != me],
        )
    box: dict = {}

    def work() -> None:
        try:
            box["parts"] = allgather_bytes(payload)
        except BaseException as err:  # noqa: BLE001 — re-raised below
            box["err"] = err

    t = threading.Thread(target=work, daemon=True, name="llmc-allgather")
    t.start()
    t.join(timeout)
    if t.is_alive():
        # Deadline passed with the collective still blocked: a peer is
        # dead or wedged. Abandon the gather (daemon thread), remember
        # every other peer as degraded, merge only ourselves.
        me, n = process_index(), process_count()
        missing = [i for i in range(n) if i != me]
        mark_degraded(missing)
        return [payload if i == me else None for i in range(n)], missing
    if "err" in box:
        raise box["err"]
    return box["parts"], []


def allgather_json_bounded(
    obj, timeout: Optional[float] = None
) -> "tuple[list, list[int]]":
    parts, missing = allgather_bytes_bounded(
        json.dumps(obj).encode("utf-8"), timeout
    )
    return (
        [None if p is None else json.loads(p.decode("utf-8")) for p in parts],
        missing,
    )


def broadcast_json(obj, owner: int):
    payload = (
        json.dumps(obj).encode("utf-8") if process_index() == owner else None
    )
    return json.loads(broadcast_bytes(payload, owner).decode("utf-8"))


# -- judge broadcast provider -------------------------------------------------


class BroadcastProvider(Provider):
    """Runs queries on the owner process; broadcasts results to the rest.

    Wraps the judge's provider under multi-controller execution: every
    process reaches the same (globally ordered) judge call sites with the
    same merged inputs, the owner does the work on its chips, and the
    response — or the error, which re-raises identically everywhere so
    control flow stays in lockstep — broadcasts over DCN.

    Degraded mode: once any peer has missed a collective deadline
    (``degraded_peers()``), the broadcast is skipped entirely and every
    surviving process serves the query from its local provider — a
    collective with a dead (or unknown-liveness) peer can only hang, and
    only process 0 emits output, so survivor-local divergence is never
    user-visible.
    """

    name = "broadcast"

    def __init__(self, inner: Provider, owner: int):
        self._inner = inner
        self._owner = owner
        self.name = getattr(inner, "name", "broadcast")

    def query(self, ctx: Context, req: Request) -> Response:
        return self.query_stream(ctx, req, None)

    def query_stream(
        self, ctx: Context, req: Request, callback: Optional[StreamCallback]
    ) -> Response:
        me = process_index()
        if degraded_peers():
            # Degraded cluster: a collective already timed out this run,
            # and a timed-out collective cannot say WHICH peers are alive
            # — so no further collectives at all. Electing a fallback
            # owner would make each survivor elect itself (every survivor
            # sees "everyone but me" as degraded) and then collide inside
            # the broadcast; and even a well-chosen owner would hang the
            # broadcast on the dead peer. Instead every survivor runs the
            # query locally: availability over lockstep, and only process
            # 0 owns output anyway (cli/main.py), so divergent survivor
            # copies are never emitted.
            return self._inner.query_stream(ctx, req, callback)
        payload: Optional[dict] = None
        if me == self._owner:
            try:
                resp = self._inner.query_stream(ctx, req, callback)
                payload = {"ok": asdict(resp)}
            except Exception as err:  # noqa: BLE001 — re-raised after sync
                payload = {"err": f"{type(err).__name__}: {err}"}
        payload = broadcast_json(payload, self._owner)
        if "err" in payload:
            raise RuntimeError(payload["err"])
        resp = Response(**payload["ok"])
        if me != self._owner and callback is not None:
            callback(resp.content)  # full-content chunk (ProviderFunc shape)
        return resp
