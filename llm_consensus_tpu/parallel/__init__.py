"""Parallelism layer: device meshes, sharding specs, and collectives.

This package is the TPU-native replacement for the reference's concurrency
story. The reference fans out goroutines over remote HTTP APIs
(/root/reference/internal/runner/runner.go:60-115); here "parallelism" is
physical: `jax.sharding.Mesh` slices carved out of the chip topology, with
panel models pinned to disjoint slices and the judge TP/EP-sharded over a
bigger one, XLA inserting collectives over ICI.

Modules:
  mesh        — topology: build meshes, carve disjoint per-model slices
  distributed — multi-host: jax.distributed init, hybrid DCN×ICI meshes
  sharding    — PartitionSpec trees for params/caches (TP + EP), shard fns
  pipeline    — GPipe-style pipeline parallelism via shard_map + ppermute
  ring        — ring attention (sequence/context parallelism) via ppermute
"""

from llm_consensus_tpu.parallel.distributed import hybrid_mesh, initialize
from llm_consensus_tpu.parallel.mesh import (
    MeshPlan,
    best_tp,
    carve_slices,
    make_mesh,
    plan_panel,
)
from llm_consensus_tpu.parallel.pipeline import pipeline_forward
from llm_consensus_tpu.parallel.ring import ring_attention
from llm_consensus_tpu.parallel.sharding import (
    cache_specs,
    make_shard_fn,
    param_specs,
    shard_pytree,
)

__all__ = [
    "MeshPlan",
    "hybrid_mesh",
    "initialize",
    "best_tp",
    "carve_slices",
    "make_mesh",
    "plan_panel",
    "cache_specs",
    "make_shard_fn",
    "param_specs",
    "pipeline_forward",
    "ring_attention",
    "shard_pytree",
]
