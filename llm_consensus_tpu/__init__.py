"""llm_consensus_tpu — a TPU-native multi-model consensus framework.

One prompt fans out to a panel of LLMs in parallel, answers stream back with
live progress, and an LLM-as-Judge synthesizes a single consensus answer.
Unlike the reference implementation (johnayoung/llm-consensus, a Go CLI over
remote HTTP APIs), panel models and the judge run on-device on TPU via
JAX/XLA: each panel model pinned to its own mesh slice over ICI, the judge
tensor-sharded across the remaining chips.

Layer map (mirrors reference layers, SURVEY.md §1):

    cli/        flag-compatible CLI               [cmd/llm-consensus/main.go]
    runner/     parallel best-effort fan-out      [internal/runner]
    consensus/  LLM-as-Judge synthesis            [internal/consensus]
    providers/  Provider protocol + registry      [internal/provider]
    engine/     TPU inference engine (new)
    models/     transformer families in functional JAX (new)
    ops/        numerics + Pallas kernels (new)
    parallel/   mesh carving, shardings, ring attention (new)
    train/      sharded training step + optimizer (new)
    distributed/ multi-host init helpers (new)
    ui/ output/ progress display; Result schema   [internal/ui, internal/output]
"""

from llm_consensus_tpu.version import __version__

__all__ = ["__version__"]
