"""Google provider — Gemini generateContent client.

Parity: /root/reference/internal/provider/google.go. POST
``{base}/models/{model}:generateContent?key=…`` — API key in the URL, model
in the path (google.go:94); streaming via ``:streamGenerateContent?…&alt=sse``
where each SSE datum is a full response and the chunk is
``candidates[0].content.parts[0].text`` (google.go:184-195). Key from
GOOGLE_API_KEY (google.go:56-59).
"""

from __future__ import annotations

import os
import time
from typing import Optional

from llm_consensus_tpu.providers.base import Provider, Request, Response, StreamCallback
from llm_consensus_tpu.providers.http_sse import post_json, stream_json_events
from llm_consensus_tpu.utils.context import Context

DEFAULT_BASE_URL = "https://generativelanguage.googleapis.com/v1beta"


class GoogleProvider(Provider):
    name = "google"

    def __init__(self, api_key: Optional[str] = None, base_url: Optional[str] = None):
        key = api_key or os.environ.get("GOOGLE_API_KEY", "")
        if not key:
            raise RuntimeError("GOOGLE_API_KEY environment variable not set")
        self._key = key
        # Env override mirrors the reference's WithGoogleBaseURL option.
        base = base_url or os.environ.get("GOOGLE_BASE_URL") or DEFAULT_BASE_URL
        self._base = base.rstrip("/")

    @staticmethod
    def _body(req: Request) -> dict:
        body = {"contents": [{"parts": [{"text": req.prompt}]}]}
        if req.system:
            body["systemInstruction"] = {"parts": [{"text": req.system}]}
        return body

    def query(self, ctx: Context, req: Request) -> Response:
        start = time.monotonic()
        url = f"{self._base}/models/{req.model}:generateContent?key={self._key}"
        data = post_json(ctx, url, {}, self._body(req))
        return Response(
            req.model, _extract_text(data), self.name, (time.monotonic() - start) * 1000
        )

    def query_stream(
        self, ctx: Context, req: Request, callback: Optional[StreamCallback]
    ) -> Response:
        start = time.monotonic()
        url = f"{self._base}/models/{req.model}:streamGenerateContent?key={self._key}&alt=sse"
        content = stream_json_events(
            ctx, url, {}, self._body(req), _extract_text_or_none, callback
        )
        return Response(req.model, content, self.name, (time.monotonic() - start) * 1000)


def _extract_text(data: dict) -> str:
    # candidates[0].content.parts[].text (google.go:189-195)
    candidates = data.get("candidates") or []
    if not candidates:
        return ""
    parts = (candidates[0].get("content") or {}).get("parts") or []
    return "".join(p.get("text", "") for p in parts)


def _extract_text_or_none(event: dict) -> Optional[str]:
    return _extract_text(event) or None
