"""Provider abstraction — the seam between orchestration and compute.

Parity: /root/reference/internal/provider/provider.go:10-55. The reference's
Provider interface {Query, QueryStream} maps to the abstract base below; its
ProviderFunc adapter (provider.go:39-55) — the seam every reference test is
built on — maps to :class:`ProviderFunc`.

One deliberate deviation: the reference marshals ``Response.Latency`` (a Go
``time.Duration``, i.e. nanoseconds) under the JSON key ``latency_ms``
(provider.go:34) — so the JSON value is in nanoseconds despite the name.
Here ``latency_ms`` genuinely holds milliseconds.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Callable, Optional

from llm_consensus_tpu.utils.context import Context

# Called once per streamed chunk of incremental text (provider.go:10).
StreamCallback = Callable[[str], None]


@dataclass(frozen=True)
class Request:
    """All inputs for one LLM query (provider.go:24-27).

    ``max_tokens`` / ``temperature`` are TPU-build extensions consumed by the
    on-device engine; HTTP providers and fakes may ignore them.
    """

    model: str
    prompt: str
    max_tokens: Optional[int] = None
    temperature: Optional[float] = None
    system: Optional[str] = None  # system prompt (TPU-build extension)
    # Priority class (pressure/priority.py: HIGH=0/NORMAL=1/LOW=2) —
    # orders continuous-batcher admission and selects preemption
    # victims. None = NORMAL; HTTP providers and fakes may ignore it.
    priority: Optional[int] = None
    # Cross-hop request trace id (obs/live.py): minted at the fleet
    # router or the gateway and threaded through runner workers into
    # engine-level spans, so one id recovers the full path of a request.
    # None outside the serving path; providers treat it as opaque.
    trace_id: Optional[str] = None
    # Live-migration resume payload (serve/elastic.py): the sealed
    # journal snapshot for THIS model's stream — {"prompt_ids": [...],
    # "sampling": {...}, "tokens": [...]} — or an emitted-text prefix
    # {"text": "..."}. Engine providers replay it through the journal
    # path (recovery/journal.py) so the resumed stream re-emits the
    # prefix and continues; providers without replay ignore it (safe:
    # deterministic decode re-derives the prefix and the router's
    # stream ledger burns the duplicate bytes).
    resume: Optional[dict] = None


@dataclass
class Response:
    """Result of one LLM query (provider.go:30-35).

    ``truncated`` is a TPU-build extension: the on-device engine sets it
    when the prompt had to be middle-out truncated to fit the model's
    context window (engine/engine.py). ``tokens`` / ``tokens_per_sec`` /
    ``mfu`` / ``mbu`` are on-device throughput measurements (utils/flops.py) — real
    generated-token counts and decode MFU, versus the reference's chars/4
    display estimate (ui.go:142). All extensions serialize only when set,
    so the reference JSON shape is unchanged in the common case.
    """

    model: str
    content: str
    provider: str
    latency_ms: float = 0.0
    truncated: bool = False
    tokens: Optional[int] = None
    tokens_per_sec: Optional[float] = None
    mfu: Optional[float] = None
    mbu: Optional[float] = None  # memory-bandwidth utilization (decode)
    # Speculative-decode telemetry for this query (rounds, accepted,
    # acceptance EMA, governor state — engine/speculative.py); None on
    # plain paths, so the reference JSON shape is unchanged without it.
    spec: Optional[dict] = None
    # KV-reuse degradation for this query: {"truncated": True} when the
    # paged pool's arena exhausted while publishing this context's
    # prefix — reuse of it is silently degraded, and operators should
    # see that per response, not only in lifetime counters.
    kv: Optional[dict] = None
    # This stream was preempted (and byte-identically resumed) at least
    # once by the pressure scheduler (engine/batcher.preempt) — the
    # live-metrics plane labels the request's latency outcome with it.
    preempted: bool = False

    def to_dict(self) -> dict:
        """JSON shape parity with the reference's Response tags."""
        d = {
            "model": self.model,
            "content": self.content,
            "provider": self.provider,
            "latency_ms": self.latency_ms,
        }
        if self.truncated:
            d["truncated"] = True
        if self.tokens is not None:
            d["tokens"] = self.tokens
        if self.tokens_per_sec is not None:
            d["tokens_per_sec"] = round(self.tokens_per_sec, 2)
        if self.mfu is not None:
            d["mfu"] = round(self.mfu, 4)
        if self.mbu is not None:
            d["mbu"] = round(self.mbu, 4)
        if self.spec is not None:
            d["spec"] = dict(self.spec)
        if self.kv is not None:
            d["kv"] = dict(self.kv)
        if self.preempted:
            d["preempted"] = True
        return d


class Provider(abc.ABC):
    """Abstracts LLM interactions — remote HTTP or on-device TPU engine."""

    def prepare(self, models: list[str], judge: Optional[str]) -> None:
        """Announce the full run composition before any query (TPU-build seam).

        The reference never needs this — each HTTP provider is stateless —
        but the on-device provider must place N panel models plus the judge
        on disjoint device-mesh slices, and slicing decisions require the
        whole panel at once (parallel/mesh.py). The CLI and bench call this
        once, after registry init and before the fan-out. Default: no-op.
        """

    @abc.abstractmethod
    def query(self, ctx: Context, req: Request) -> Response:
        """Send a prompt and return the complete response."""

    @abc.abstractmethod
    def query_stream(
        self, ctx: Context, req: Request, callback: Optional[StreamCallback]
    ) -> Response:
        """Send a prompt, invoking ``callback`` per chunk; returns the full response."""


class ProviderFunc(Provider):
    """Function adapter implementing Provider (provider.go:39-55).

    ``query_stream`` calls the function once and fires the callback with the
    full content — exactly the reference adapter's behavior, which tests and
    simple providers rely on.
    """

    def __init__(self, fn: Callable[[Context, Request], Response]):
        self._fn = fn

    def query(self, ctx: Context, req: Request) -> Response:
        return self._fn(ctx, req)

    def query_stream(
        self, ctx: Context, req: Request, callback: Optional[StreamCallback]
    ) -> Response:
        resp = self.query(ctx, req)
        if callback is not None:
            callback(resp.content)
        return resp
