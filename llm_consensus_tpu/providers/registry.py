"""Thread-safe model-name → Provider registry, plus the remote catalog.

Parity: /root/reference/internal/provider/registry.go:10-53 — RWMutex-guarded
map with Register / Get (unknown-model error) / Models.

The remote-API model catalog (reference main.go:49-61) lives here rather
than in the CLI so non-CLI consumers — the router tier's spillover lane
in particular — can build a registry of OpenAI/Anthropic/Google providers
without importing the CLI layer: :data:`REMOTE_MODELS` maps model name →
provider kind, :func:`create_remote_provider` builds the provider, and
:func:`remote_registry` assembles a whole panel+judge registry.
"""

from __future__ import annotations

import threading
from typing import Optional

from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.providers.base import Provider

# Known remote models → provider kind (reference main.go:49-61). The CLI
# layers the `tpu:` scheme and aliases on top; this table is only the
# remote-API catalog.
REMOTE_MODELS: dict[str, str] = {
    "gpt-5.2-2025-12-11": "openai",
    "gpt-5.2-pro-2025-12-11": "openai",
    "claude-sonnet-4-5": "anthropic",
    "claude-haiku-4-5": "anthropic",
    "claude-opus-4-5": "anthropic",
    "gemini-3-pro-preview": "google",
}


def create_remote_provider(model: str) -> Provider:
    """Build the HTTP provider serving a :data:`REMOTE_MODELS` entry."""
    kind = REMOTE_MODELS.get(model)
    if kind is None:
        raise ValueError(
            f"unknown remote model {model!r}; "
            f"available: {sorted(REMOTE_MODELS)}"
        )
    if kind == "openai":
        from llm_consensus_tpu.providers.openai import OpenAIProvider

        return OpenAIProvider()
    if kind == "anthropic":
        from llm_consensus_tpu.providers.anthropic import AnthropicProvider

        return AnthropicProvider()
    from llm_consensus_tpu.providers.google import GoogleProvider

    return GoogleProvider()


def remote_registry(models: list[str], judge: Optional[str]) -> "Registry":
    """One remote provider per unique model, judge included — the
    spillover lane's registry (all names must be in REMOTE_MODELS)."""
    registry = Registry()
    for model in dict.fromkeys(models + ([judge] if judge else [])):
        registry.register(model, create_remote_provider(model))
    return registry


class UnknownModelError(KeyError):
    """Raised by :meth:`Registry.get` for an unregistered model (registry.go:36-39)."""

    def __init__(self, model: str, available: list[str]):
        self.model = model
        self.available = available
        super().__init__(model)

    def __str__(self) -> str:
        return f"unknown model {self.model!r}; registered models: {self.available}"


class Registry:
    """Maps model names to the Provider serving them."""

    def __init__(self) -> None:
        self._lock = sanitizer.make_rlock("providers.registry")
        self._providers: dict[str, Provider] = {}

    def register(self, model: str, provider: Provider) -> None:
        with self._lock:
            self._providers[model] = provider

    def get(self, model: str) -> Provider:
        with self._lock:
            try:
                return self._providers[model]
            except KeyError:
                raise UnknownModelError(model, sorted(self._providers)) from None

    def models(self) -> list[str]:
        with self._lock:
            return sorted(self._providers)

    def __contains__(self, model: str) -> bool:
        with self._lock:
            return model in self._providers
