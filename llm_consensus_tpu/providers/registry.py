"""Thread-safe model-name → Provider registry.

Parity: /root/reference/internal/provider/registry.go:10-53 — RWMutex-guarded
map with Register / Get (unknown-model error) / Models.
"""

from __future__ import annotations

import threading

from llm_consensus_tpu.providers.base import Provider


class UnknownModelError(KeyError):
    """Raised by :meth:`Registry.get` for an unregistered model (registry.go:36-39)."""

    def __init__(self, model: str, available: list[str]):
        self.model = model
        self.available = available
        super().__init__(model)

    def __str__(self) -> str:
        return f"unknown model {self.model!r}; registered models: {self.available}"


class Registry:
    """Maps model names to the Provider serving them."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._providers: dict[str, Provider] = {}

    def register(self, model: str, provider: Provider) -> None:
        with self._lock:
            self._providers[model] = provider

    def get(self, model: str) -> Provider:
        with self._lock:
            try:
                return self._providers[model]
            except KeyError:
                raise UnknownModelError(model, sorted(self._providers)) from None

    def models(self) -> list[str]:
        with self._lock:
            return sorted(self._providers)

    def __contains__(self, model: str) -> bool:
        with self._lock:
            return model in self._providers
