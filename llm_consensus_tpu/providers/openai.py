"""OpenAI provider — Responses API client.

Parity: /root/reference/internal/provider/openai.go. POST {base}/responses
with {model, input, stream}; streaming accumulates
``response.output_text.delta`` events; non-streaming walks
``output[].content[]`` for ``type == "output_text"`` (openai.go:249-261).
API key from OPENAI_API_KEY at construction (openai.go:63-67); base URL
injectable for tests/proxies (openai.go:52-58).
"""

from __future__ import annotations

import os
import time
from typing import Optional

from llm_consensus_tpu.providers.base import Provider, Request, Response, StreamCallback
from llm_consensus_tpu.providers.http_sse import post_json, stream_json_events
from llm_consensus_tpu.utils.context import Context

DEFAULT_BASE_URL = "https://api.openai.com/v1"


class OpenAIProvider(Provider):
    name = "openai"

    def __init__(self, api_key: Optional[str] = None, base_url: Optional[str] = None):
        key = api_key or os.environ.get("OPENAI_API_KEY", "")
        if not key:
            raise RuntimeError("OPENAI_API_KEY environment variable not set")
        self._key = key
        # Env override is the CLI-reachable analog of the reference's
        # WithOpenAIBaseURL test/proxy option (openai.go:52-58).
        base = base_url or os.environ.get("OPENAI_BASE_URL") or DEFAULT_BASE_URL
        self._base = base.rstrip("/")

    def _headers(self) -> dict[str, str]:
        return {"Authorization": f"Bearer {self._key}"}

    def _body(self, req: Request, stream: bool) -> dict:
        body = {"model": req.model, "input": req.prompt}
        if req.system:
            body["instructions"] = req.system
        if stream:
            body["stream"] = True
        return body

    def query(self, ctx: Context, req: Request) -> Response:
        start = time.monotonic()
        data = post_json(ctx, f"{self._base}/responses", self._headers(), self._body(req, False))
        content = _extract_response_text(data)
        return Response(req.model, content, self.name, (time.monotonic() - start) * 1000)

    def query_stream(
        self, ctx: Context, req: Request, callback: Optional[StreamCallback]
    ) -> Response:
        start = time.monotonic()
        content = stream_json_events(
            ctx,
            f"{self._base}/responses",
            self._headers(),
            self._body(req, True),
            _extract_delta,
            callback,
        )
        return Response(req.model, content, self.name, (time.monotonic() - start) * 1000)


def _extract_delta(event: dict) -> Optional[str]:
    # Only response.output_text.delta events carry text (openai.go:192-197).
    if event.get("type") == "response.output_text.delta":
        return event.get("delta") or None
    return None


def _extract_response_text(data: dict) -> str:
    # Walk output[].content[] collecting output_text items (openai.go:249-261).
    parts = []
    for item in data.get("output", []):
        for content in item.get("content", []):
            if content.get("type") == "output_text":
                parts.append(content.get("text", ""))
    return "".join(parts)
