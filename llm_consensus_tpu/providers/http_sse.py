"""Shared HTTP + SSE plumbing for the remote-API providers.

The reference implements three structurally-identical HTTP clients
(/root/reference/internal/provider/{openai,anthropic,google}.go): POST JSON,
non-2xx → error with body, and for streaming a line loop over the response
body keeping ``data: `` SSE payloads. This module factors that shared shape
out once; each provider supplies only its endpoint, headers, request body,
and event-extraction functions.

Deviation from the reference (deliberate): requests honor the run's
cancellation context and size the socket timeout to the context deadline,
instead of a fixed 60 s client timeout (openai.go:72). The transport is
``http.client`` rather than ``urllib`` so the connection object exists
*before* the request is sent — cancellation can then abort any phase
(connect, waiting for headers, body read) by closing the socket from the
``ctx.on_done`` hook.
"""

from __future__ import annotations

import http.client
import json
import urllib.parse
from typing import Callable, Iterator, Optional

from llm_consensus_tpu.utils.context import Context

DEFAULT_TIMEOUT_S = 60.0  # connection-level default, as the reference's HTTP client


class HTTPError(RuntimeError):
    """Non-2xx API response, carrying status and (truncated) body."""

    def __init__(self, status: int, body: str):
        self.status = status
        self.body = body
        super().__init__(f"API request failed with status {status}: {body[:500]}")


def _socket_timeout(ctx: Context) -> float:
    # The context deadline governs when one exists; the 60 s default only
    # bounds requests with no deadline at all.
    rem = ctx.remaining()
    if rem is None:
        return DEFAULT_TIMEOUT_S
    return max(0.001, rem)


def _connect(
    ctx: Context, url: str, headers: dict[str, str], body: dict, accept: Optional[str]
):
    """Open a connection, send the POST, return (conn, resp, unsubscribe).

    The ``ctx.on_done`` hook closes the *connection* (not just the response),
    so cancellation interrupts every blocking phase — including the wait for
    response headers, which for a non-streaming LLM call is most of the
    request's lifetime. On cancellation the blocked read raises an OSError
    subclass, which callers translate back via ``ctx.raise_if_done()``.
    """
    ctx.raise_if_done()
    parsed = urllib.parse.urlsplit(url)
    conn_cls = (
        http.client.HTTPSConnection if parsed.scheme == "https" else http.client.HTTPConnection
    )
    conn = conn_cls(parsed.netloc, timeout=_socket_timeout(ctx))
    unsubscribe = ctx.on_done(conn.close)
    path = parsed.path + (f"?{parsed.query}" if parsed.query else "")
    all_headers = {"Content-Type": "application/json", **headers}
    if accept:
        all_headers["Accept"] = accept
    try:
        conn.request("POST", path, body=json.dumps(body).encode("utf-8"), headers=all_headers)
        resp = conn.getresponse()
    except (http.client.HTTPException, ValueError, OSError) as err:
        unsubscribe()
        conn.close()
        ctx.raise_if_done()  # closed by cancellation → surface the ctx error
        raise RuntimeError(f"request failed: {err}") from None
    if not 200 <= resp.status < 300:
        status = resp.status
        body_text = resp.read().decode("utf-8", "replace")
        unsubscribe()
        conn.close()
        raise HTTPError(status, body_text)
    return conn, resp, unsubscribe


def post_json(ctx: Context, url: str, headers: dict[str, str], body: dict) -> dict:
    """POST a JSON body, return the parsed JSON response."""
    conn, resp, unsubscribe = _connect(ctx, url, headers, body, accept=None)
    try:
        raw = resp.read()
        ctx.raise_if_done()  # close race: a cancelled read can return b""
        return json.loads(raw.decode("utf-8"))
    except (ValueError, OSError) as err:
        ctx.raise_if_done()
        raise RuntimeError(f"reading response failed: {err}") from None
    finally:
        unsubscribe()
        conn.close()


def post_sse(
    ctx: Context, url: str, headers: dict[str, str], body: dict
) -> Iterator[str]:
    """POST a JSON body and yield each SSE ``data:`` payload string.

    Stops at stream end or a ``[DONE]`` sentinel; checks the cancellation
    context between lines (the hot loop — reference openai.go:175-198). A
    cancellation mid-stream always raises (never returns a truncated stream
    as if complete): closing the socket either errors the blocked read or
    ends iteration early, and both paths re-check the context.
    """
    conn, resp, unsubscribe = _connect(ctx, url, headers, body, accept="text/event-stream")
    try:
        for raw in resp:
            ctx.raise_if_done()
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data: "):
                continue  # skip comments, event: lines, blanks
            data = line[len("data: "):]
            if data == "[DONE]":
                return
            yield data
        ctx.raise_if_done()  # close race: cancellation can end the stream cleanly
    except (ValueError, OSError):
        ctx.raise_if_done()  # closed by cancellation → surface the ctx error
        raise
    finally:
        unsubscribe()
        conn.close()


def stream_json_events(
    ctx: Context,
    url: str,
    headers: dict[str, str],
    body: dict,
    extract: Callable[[dict], Optional[str]],
    callback: Optional[Callable[[str], None]],
) -> str:
    """Drive an SSE stream, extracting a text delta per event.

    ``extract`` returns the chunk for an event or None to skip it (malformed
    events are skipped, matching the reference's lenient parsing). Returns
    the accumulated full content.
    """
    parts: list[str] = []
    for data in post_sse(ctx, url, headers, body):
        try:
            event = json.loads(data)
        except json.JSONDecodeError:
            continue
        chunk = extract(event)
        if chunk:
            parts.append(chunk)
            if callback is not None:
                callback(chunk)
    return "".join(parts)
