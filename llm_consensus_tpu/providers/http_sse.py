"""Shared HTTP + SSE plumbing for the remote-API providers.

The reference implements three structurally-identical HTTP clients
(/root/reference/internal/provider/{openai,anthropic,google}.go): POST JSON,
non-2xx → error with body, and for streaming a line loop over the response
body keeping ``data: `` SSE payloads. This module factors that shared shape
out once; each provider supplies only its endpoint, headers, request body,
and event-extraction functions.

Deviation from the reference (deliberate): requests honor the run's
cancellation context between SSE lines and size the socket timeout to the
context deadline, instead of a fixed 60 s client timeout (openai.go:72).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Callable, Iterator, Optional

from llm_consensus_tpu.utils.context import Context

DEFAULT_TIMEOUT_S = 60.0  # connection-level default, as the reference's HTTP client


class HTTPError(RuntimeError):
    """Non-2xx API response, carrying status and (truncated) body."""

    def __init__(self, status: int, body: str):
        self.status = status
        self.body = body
        super().__init__(f"API request failed with status {status}: {body[:500]}")


def _socket_timeout(ctx: Context) -> float:
    # The context deadline governs when one exists; the 60 s default only
    # bounds requests with no deadline at all.
    rem = ctx.remaining()
    if rem is None:
        return DEFAULT_TIMEOUT_S
    return max(0.001, rem)


def post_json(ctx: Context, url: str, headers: dict[str, str], body: dict) -> dict:
    """POST a JSON body, return the parsed JSON response.

    Cancellation closes the underlying response (via ``ctx.on_done``), so a
    blocked read wakes immediately on Ctrl-C rather than waiting out the
    socket timeout.
    """
    ctx.raise_if_done()
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json", **headers},
        method="POST",
    )
    holder: dict = {}
    unsubscribe = ctx.on_done(lambda: holder.get("resp") and holder["resp"].close())
    try:
        with urllib.request.urlopen(req, timeout=_socket_timeout(ctx)) as resp:
            holder["resp"] = resp
            ctx.raise_if_done()
            return json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as err:
        raise HTTPError(err.code, err.read().decode("utf-8", "replace")) from None
    except urllib.error.URLError as err:
        ctx.raise_if_done()
        raise RuntimeError(f"request failed: {err.reason}") from None
    except (ValueError, OSError):
        ctx.raise_if_done()  # closed by cancellation → surface the ctx error
        raise
    finally:
        unsubscribe()


def post_sse(
    ctx: Context, url: str, headers: dict[str, str], body: dict
) -> Iterator[str]:
    """POST a JSON body and yield each SSE ``data:`` payload string.

    Stops at stream end or a ``[DONE]`` sentinel; checks the cancellation
    context between lines (the hot loop — reference openai.go:175-198).
    """
    ctx.raise_if_done()
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json", "Accept": "text/event-stream", **headers},
        method="POST",
    )
    try:
        resp = urllib.request.urlopen(req, timeout=_socket_timeout(ctx))
    except urllib.error.HTTPError as err:
        raise HTTPError(err.code, err.read().decode("utf-8", "replace")) from None
    except urllib.error.URLError as err:
        ctx.raise_if_done()
        raise RuntimeError(f"request failed: {err.reason}") from None

    # Cancellation closes the stream so a blocked line read wakes instantly.
    unsubscribe = ctx.on_done(resp.close)
    try:
        with resp:
            for raw in resp:
                ctx.raise_if_done()
                line = raw.decode("utf-8", "replace").strip()
                if not line.startswith("data: "):
                    continue  # skip comments, event: lines, blanks
                data = line[len("data: "):]
                if data == "[DONE]":
                    return
                yield data
    except (ValueError, OSError):
        ctx.raise_if_done()  # closed by cancellation → surface the ctx error
        raise
    finally:
        unsubscribe()


def stream_json_events(
    ctx: Context,
    url: str,
    headers: dict[str, str],
    body: dict,
    extract: Callable[[dict], Optional[str]],
    callback: Optional[Callable[[str], None]],
) -> str:
    """Drive an SSE stream, extracting a text delta per event.

    ``extract`` returns the chunk for an event or None to skip it (malformed
    events are skipped, matching the reference's lenient parsing). Returns
    the accumulated full content.
    """
    parts: list[str] = []
    for data in post_sse(ctx, url, headers, body):
        try:
            event = json.loads(data)
        except json.JSONDecodeError:
            continue
        chunk = extract(event)
        if chunk:
            parts.append(chunk)
            if callback is not None:
                callback(chunk)
    return "".join(parts)
