"""Shared HTTP + SSE plumbing for the remote-API providers.

The reference implements three structurally-identical HTTP clients
(/root/reference/internal/provider/{openai,anthropic,google}.go): POST JSON,
non-2xx → error with body, and for streaming a line loop over the response
body keeping ``data: `` SSE payloads. This module factors that shared shape
out once; each provider supplies only its endpoint, headers, request body,
and event-extraction functions.

Deviation from the reference (deliberate): requests honor the run's
cancellation context and size the socket timeout to the context deadline,
instead of a fixed 60 s client timeout (openai.go:72). The transport is
``http.client`` rather than ``urllib`` so the connection object exists
*before* the request is sent — cancellation can then abort any phase
(connect, waiting for headers, body read) by closing the socket from the
``ctx.on_done`` hook.
"""

from __future__ import annotations

import http.client
import json
import urllib.parse
from typing import Callable, Iterator, Optional


from llm_consensus_tpu.utils.context import Context
from llm_consensus_tpu.utils import knobs

DEFAULT_TIMEOUT_S = 60.0  # connection-level default, as the reference's HTTP client

# Retry-with-backoff (reference roadmap §4, unimplemented there).
# Transient statuses: timeout, conflict, rate limit, server errors.
RETRYABLE_STATUS = frozenset({408, 409, 429, 500, 502, 503, 504})


class TransientHTTPError(RuntimeError):
    """A connection-phase or mid-transfer failure worth retrying."""


def _max_attempts() -> int:
    return 1 + max(0, knobs.get_int("LLMC_HTTP_RETRIES"))


def _backoff_s(attempt: int) -> float:
    return knobs.get_float("LLMC_HTTP_BACKOFF") * (2 ** attempt)


def _retryable(err: Exception) -> bool:
    if isinstance(err, HTTPError):
        return err.status in RETRYABLE_STATUS
    return isinstance(err, TransientHTTPError)


def _with_retries(ctx: Context, fn, delivered=None):
    """Run ``fn`` with exponential-backoff retries on transient failures.

    ``delivered`` (when given) vetoes a retry once output already reached
    the caller — restarting then would emit content twice. Cancellation
    (Cancelled/DeadlineExceeded are not RuntimeErrors) always escapes.
    """
    attempts = _max_attempts()
    for attempt in range(attempts):
        try:
            return fn()
        except (HTTPError, TransientHTTPError) as err:
            if (
                (delivered is not None and delivered())
                or attempt == attempts - 1
                or not _retryable(err)
            ):
                raise
            if not ctx.sleep(_backoff_s(attempt)):
                ctx.raise_if_done()
    raise AssertionError("unreachable")


class HTTPError(RuntimeError):
    """Non-2xx API response, carrying status and (truncated) body."""

    def __init__(self, status: int, body: str):
        self.status = status
        self.body = body
        super().__init__(f"API request failed with status {status}: {body[:500]}")


def _socket_timeout(ctx: Context) -> float:
    # The context deadline governs when one exists; the 60 s default only
    # bounds requests with no deadline at all.
    rem = ctx.remaining()
    if rem is None:
        return DEFAULT_TIMEOUT_S
    return max(0.001, rem)


def _connect(
    ctx: Context, url: str, headers: dict[str, str], body: dict, accept: Optional[str]
):
    """Open a connection, send the POST, return (conn, resp, unsubscribe).

    The ``ctx.on_done`` hook closes the *connection* (not just the response),
    so cancellation interrupts every blocking phase — including the wait for
    response headers, which for a non-streaming LLM call is most of the
    request's lifetime. On cancellation the blocked read raises an OSError
    subclass, which callers translate back via ``ctx.raise_if_done()``.
    """
    ctx.raise_if_done()
    parsed = urllib.parse.urlsplit(url)
    conn_cls = (
        http.client.HTTPSConnection if parsed.scheme == "https" else http.client.HTTPConnection
    )
    conn = conn_cls(parsed.netloc, timeout=_socket_timeout(ctx))
    unsubscribe = ctx.on_done(conn.close)
    path = parsed.path + (f"?{parsed.query}" if parsed.query else "")
    all_headers = {"Content-Type": "application/json", **headers}
    if accept:
        all_headers["Accept"] = accept
    try:
        conn.request("POST", path, body=json.dumps(body).encode("utf-8"), headers=all_headers)
        resp = conn.getresponse()
    except (http.client.HTTPException, ValueError, OSError) as err:
        unsubscribe()
        conn.close()
        ctx.raise_if_done()  # closed by cancellation → surface the ctx error
        raise TransientHTTPError(f"request failed: {err}") from None
    if not 200 <= resp.status < 300:
        status = resp.status
        body_text = resp.read().decode("utf-8", "replace")
        unsubscribe()
        conn.close()
        raise HTTPError(status, body_text)
    return conn, resp, unsubscribe


def post_json(ctx: Context, url: str, headers: dict[str, str], body: dict) -> dict:
    """POST a JSON body, return the parsed JSON response.

    Transient failures (connection errors, 408/409/429/5xx) retry with
    exponential backoff — ``LLMC_HTTP_RETRIES`` attempts (default 2) at
    ``LLMC_HTTP_BACKOFF``·2ⁿ seconds — honoring the cancellation context
    during the wait.
    """
    return _with_retries(ctx, lambda: _post_json_once(ctx, url, headers, body))


def _post_json_once(ctx: Context, url: str, headers: dict[str, str], body: dict) -> dict:
    conn, resp, unsubscribe = _connect(ctx, url, headers, body, accept=None)
    try:
        raw = resp.read()
        ctx.raise_if_done()  # close race: a cancelled read can return b""
        return json.loads(raw.decode("utf-8"))
    except json.JSONDecodeError as err:
        raise RuntimeError(f"invalid JSON response: {err}") from None
    except (ValueError, OSError, http.client.HTTPException) as err:
        ctx.raise_if_done()
        # Nothing was returned to the caller, so a mid-body connection
        # reset is as retryable as a connect failure.
        raise TransientHTTPError(f"reading response failed: {err}") from None
    finally:
        unsubscribe()
        conn.close()


def post_sse(
    ctx: Context, url: str, headers: dict[str, str], body: dict
) -> Iterator[str]:
    """POST a JSON body and yield each SSE ``data:`` payload string.

    Stops at stream end or a ``[DONE]`` sentinel; checks the cancellation
    context between lines (the hot loop — reference openai.go:175-198). A
    cancellation mid-stream always raises (never returns a truncated stream
    as if complete): closing the socket either errors the blocked read or
    ends iteration early, and both paths re-check the context.
    """
    from llm_consensus_tpu import faults, obs

    fault_plan = faults.plan()  # resolved once per process; None when off
    obs_r = obs.recorder()      # same pattern: one None-check per event
    conn, resp, unsubscribe = _connect(ctx, url, headers, body, accept="text/event-stream")
    saw_data = False
    try:
        for raw in resp:
            ctx.raise_if_done()
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data: "):
                continue  # skip comments, event: lines, blanks
            data = line[len("data: "):]
            if data == "[DONE]":
                return
            saw_data = True
            if obs_r is not None:
                # Chunk arrival on the run timeline: inter-instant gaps
                # are the remote provider's streaming cadence.
                obs_r.instant("sse_chunk", tid="sse", bytes=len(data))
            if fault_plan is not None:
                # sse_reset@chunk=N: the Nth data event at this site
                # (one process-wide counter across all streams, like
                # every fault site — deterministic for a sequential call
                # order) is replaced by a mid-transfer reset — the same
                # transient shape a dropped connection produces, so it
                # rides the real retry/delivered-veto machinery.
                fs = fault_plan.fire("sse")
                if fs is not None:
                    raise TransientHTTPError(
                        f"injected mid-stream reset ({fs.kind})"
                    )
            yield data
        ctx.raise_if_done()  # close race: cancellation can end the stream cleanly
        if not saw_data:
            # A connection torn down right after the headers reads as a
            # clean EOF (readline returns b"") — surface the silently
            # empty stream as transient instead of an empty answer.
            raise TransientHTTPError("stream ended before any data arrived")
    except (ValueError, OSError, http.client.HTTPException) as err:
        ctx.raise_if_done()  # closed by cancellation → surface the ctx error
        # Mid-stream resets and short reads (IncompleteRead) are
        # transient; whether a retry is safe is the consumer's call (it
        # knows if chunks were already delivered).
        raise TransientHTTPError(f"stream failed: {err}") from None
    finally:
        unsubscribe()
        conn.close()


def stream_json_events(
    ctx: Context,
    url: str,
    headers: dict[str, str],
    body: dict,
    extract: Callable[[dict], Optional[str]],
    callback: Optional[Callable[[str], None]],
) -> str:
    """Drive an SSE stream, extracting a text delta per event.

    ``extract`` returns the chunk for an event or None to skip it (malformed
    events are skipped, matching the reference's lenient parsing). Returns
    the accumulated full content.

    Transient failures retry like :func:`post_json` — but only while no
    chunk has been delivered yet: once text reached the callback (and the
    live UI), a silent restart would emit the answer twice.
    """
    parts: list[str] = []

    def attempt() -> str:
        parts.clear()
        for data in post_sse(ctx, url, headers, body):
            try:
                event = json.loads(data)
            except json.JSONDecodeError:
                continue
            chunk = extract(event)
            if chunk:
                parts.append(chunk)
                if callback is not None:
                    callback(chunk)
        return "".join(parts)

    return _with_retries(ctx, attempt, delivered=lambda: bool(parts))
