"""Anthropic provider — Messages API client.

Parity: /root/reference/internal/provider/anthropic.go. POST {base}/messages
with max_tokens 4096 (anthropic.go:79,137), headers ``x-api-key`` +
``anthropic-version: 2023-06-01`` (anthropic.go:95-97); streaming keeps
``content_block_delta``/``text_delta`` events (anthropic.go:183-189). Key
from ANTHROPIC_API_KEY (anthropic.go:55-58).
"""

from __future__ import annotations

import os
import time
from typing import Optional

from llm_consensus_tpu.providers.base import Provider, Request, Response, StreamCallback
from llm_consensus_tpu.providers.http_sse import post_json, stream_json_events
from llm_consensus_tpu.utils.context import Context

DEFAULT_BASE_URL = "https://api.anthropic.com/v1"
MAX_TOKENS = 4096  # hardcoded in the reference (anthropic.go:79)
API_VERSION = "2023-06-01"


class AnthropicProvider(Provider):
    name = "anthropic"

    def __init__(self, api_key: Optional[str] = None, base_url: Optional[str] = None):
        key = api_key or os.environ.get("ANTHROPIC_API_KEY", "")
        if not key:
            raise RuntimeError("ANTHROPIC_API_KEY environment variable not set")
        self._key = key
        # Env override mirrors the reference's WithAnthropicBaseURL option.
        base = base_url or os.environ.get("ANTHROPIC_BASE_URL") or DEFAULT_BASE_URL
        self._base = base.rstrip("/")

    def _headers(self) -> dict[str, str]:
        return {"x-api-key": self._key, "anthropic-version": API_VERSION}

    def _body(self, req: Request, stream: bool) -> dict:
        body = {
            "model": req.model,
            "max_tokens": MAX_TOKENS,
            "messages": [{"role": "user", "content": req.prompt}],
        }
        if req.system:
            body["system"] = req.system
        if stream:
            body["stream"] = True
        return body

    def query(self, ctx: Context, req: Request) -> Response:
        start = time.monotonic()
        data = post_json(ctx, f"{self._base}/messages", self._headers(), self._body(req, False))
        parts = [b.get("text", "") for b in data.get("content", []) if b.get("type") == "text"]
        return Response(req.model, "".join(parts), self.name, (time.monotonic() - start) * 1000)

    def query_stream(
        self, ctx: Context, req: Request, callback: Optional[StreamCallback]
    ) -> Response:
        start = time.monotonic()
        content = stream_json_events(
            ctx,
            f"{self._base}/messages",
            self._headers(),
            self._body(req, True),
            _extract_delta,
            callback,
        )
        return Response(req.model, content, self.name, (time.monotonic() - start) * 1000)


def _extract_delta(event: dict) -> Optional[str]:
    # content_block_delta events with a text_delta carry text (anthropic.go:183-189).
    if event.get("type") == "content_block_delta":
        delta = event.get("delta", {})
        if delta.get("type") == "text_delta":
            return delta.get("text") or None
    return None
