"""The ``tpu`` provider — on-device inference behind the Provider seam.

This is the whole point of the framework (SURVEY.md §7): where the reference
routes a model name to an HTTP client (/root/reference/cmd/llm-consensus/
main.go:417-438), ``tpu:<model>`` routes to an on-device JAX engine. The
rest of the stack — runner fan-out, judge, UI streaming — is unchanged, so
panel models and the judge run locally with zero outbound API calls.

Model names: ``tpu:<preset>`` for any preset in the model catalog
(models/config.py), e.g. ``tpu:llama-3-8b``, ``tpu:consensus-1b``,
``tpu:tiny-llama``. Engines are created lazily, once per model, and shared
across panel/judge uses (thread-safe: generate state is per-call).

Weights: loaded from ``$LLMC_CHECKPOINT_DIR/<preset>/`` when present
(engine/checkpoint.py), else random-initialized — which keeps the full
pipeline drivable on any chip (and is what the benchmark harness uses).
Generation defaults mirror the reference's only output cap, Anthropic's
hardcoded 4096 max tokens (/root/reference/internal/provider/anthropic.go:79).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.providers.base import Provider, Request, Response, StreamCallback
from llm_consensus_tpu.utils.context import Cancelled, Context, DeadlineExceeded
from llm_consensus_tpu.utils import knobs

DEFAULT_MAX_NEW_TOKENS = 4096
SCHEME = "tpu:"

_cache_enabled = False


def _enable_compilation_cache() -> None:
    """Persist XLA compilations across processes (first-run UX).

    A fresh CLI process pays 20-40s of compile per model×bucket on a real
    chip; the on-disk cache makes every later invocation start decoding
    immediately. ``LLMC_XLA_CACHE=0`` disables, ``LLMC_XLA_CACHE=<dir>``
    relocates. Best-effort: failure to set up the cache never blocks
    serving.
    """
    global _cache_enabled
    if _cache_enabled:
        return
    _cache_enabled = True
    env = knobs.get_str("LLMC_XLA_CACHE")
    if env == "0":
        return
    cache_dir = env or os.path.join(
        os.path.expanduser("~"), ".cache", "llm-consensus-tpu", "xla"
    )
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


def _parse_draft_spec(spec: str) -> dict:
    """LLMC_DRAFT → {target preset: draft preset}.

    ``"tiny-llama"`` drafts for every target (``"*"`` key);
    ``"consensus-3b=consensus-1b,big=small"`` names per-target pairs.
    The special draft value ``"lookup"`` names the prompt-lookup n-gram
    drafter (engine/speculative.py) instead of a second model: zero
    draft cost, composes with continuous batching AND sharded targets
    (it carries no second KV cache), and wins exactly on the judge's
    quote-the-panel workload. Presets are validated lazily at engine
    build (a typo'd draft should fail the request that needs it, not the
    whole provider).
    """
    spec = (spec or "").strip()
    if not spec:
        return {}
    out: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            target, _, draft = part.partition("=")
            out[target.strip()] = draft.strip()
        else:
            out["*"] = part
    return out


def parse_model_name(model: str) -> str:
    """``tpu:<preset>`` → preset name; validates against the catalog."""
    from llm_consensus_tpu.models.config import MODEL_PRESETS

    name = model[len(SCHEME):] if model.startswith(SCHEME) else model
    if name not in MODEL_PRESETS:
        available = [f"tpu:{m}" for m in sorted(MODEL_PRESETS)]
        raise ValueError(f"unknown tpu model {model!r}; available: {available}")
    return name


class TPUProvider(Provider):
    """Serves every ``tpu:*`` model from a lazily-built engine pool."""

    name = "tpu"
    _shared: Optional["TPUProvider"] = None
    _shared_lock = sanitizer.make_lock("providers.tpu.shared")
    # utilization_stats delta-window floor: calls inside it replay the
    # last computed entry instead of advancing the window (concurrent
    # /statsz + /metricsz consumers share one delta state).
    _UTIL_MIN_WINDOW_S = 1.0

    def __init__(
        self,
        *,
        checkpoint_dir: Optional[str] = None,
        stream_interval: int = 16,
        ignore_eos: bool = False,
        quant: Optional[str] = None,
        kv_quant: Optional[str] = None,
        batch_streams: int = 1,
        draft: Optional[str] = None,
        max_seq: Optional[int] = None,
        prefill_budget: Optional[int] = None,
        disagg: Optional[bool] = None,
    ):
        self._engines: dict[str, object] = {}
        self._meshes: dict[str, object] = {}  # preset -> jax.sharding.Mesh
        self._lock = sanitizer.make_lock("providers.tpu")
        self._build_locks: dict = {}
        self._checkpoint_dir = (
            checkpoint_dir or knobs.get_str("LLMC_CHECKPOINT_DIR") or None
        )
        self._stream_interval = stream_interval
        # Fixed-length decode for benchmarking (bench.py); never ambient.
        self._ignore_eos = ignore_eos
        # Quantization modes for every engine this provider builds
        # (None → Engine reads LLMC_QUANT / LLMC_KV_QUANT itself).
        self._quant = quant
        self._kv_quant = kv_quant
        # batch_streams > 1: concurrent requests for the SAME model route
        # through a per-engine ContinuousBatcher (decode is HBM-bound, so
        # co-resident streams share the weight stream nearly for free).
        # Greedy results stay token-exact vs the direct path. Env default
        # lets a serving deployment flip it on without code changes:
        # LLMC_MAX_BATCH (the serving gateway's knob — `serve --max-batch`
        # validates against it) with LLMC_BATCH_STREAMS as the original
        # spelling.
        self._batch_streams = batch_streams if batch_streams > 1 else (
            knobs.get_int("LLMC_MAX_BATCH", 0)
            or knobs.get_int("LLMC_BATCH_STREAMS")
        )
        self._batchers: dict[str, object] = {}  # preset -> (engine, batcher)
        # Interleaved admission prefill (prefill/decode overlap): > 0
        # makes every batcher this provider builds split admission
        # prefills into LLMC_PREFILL_BUDGET-token credit chunks
        # dispatched between decode chunks, so resident streams keep
        # decoding while new ones establish. None → the batcher reads
        # LLMC_PREFILL_BUDGET itself; 0 forces the classic
        # stall-the-pool admission.
        self._prefill_budget = prefill_budget
        # Speculative decoding (engine/speculative.py): ``draft`` /
        # LLMC_DRAFT attaches a draft preset per target —
        # "tiny-llama" drafts for every model, or
        # "consensus-3b=consensus-1b,..." per-target pairs. Greedy output
        # is token-exact vs the plain path (the draft only changes speed),
        # so the flag is safe to flip on any serving deployment.
        self._draft_map = _parse_draft_spec(
            draft if draft is not None else knobs.get_str("LLMC_DRAFT")
        )
        self._spec_k = max(1, knobs.get_int("LLMC_SPEC_K"))
        self._spec_ngram = max(1, knobs.get_int("LLMC_SPEC_NGRAM"))
        self._specs: dict[str, tuple] = {}  # preset -> (engine, SpeculativeEngine)
        # Devices that failed a model twice (elastic re-placement,
        # _replace_engine): excluded from future prepare() plans so a
        # re-placed model is not handed back its wedged chips next run.
        self._bad_devices: set[int] = set()
        # Context-capacity budget: caps every engine's max_seq below the
        # preset's full window (LLMC_MAX_SEQ env as the deployment knob).
        # KV-cache HBM is proportional to capacity — a serving tier that
        # never sees 4k-token conversations should not reserve 4k-token
        # caches, and the continuous batcher multiplies the cost by its
        # slot count.
        if max_seq is None:
            max_seq = knobs.get_int("LLMC_MAX_SEQ") or None
        self._max_seq = max_seq
        # Real generated-token counts (vs the UI's chars/4 estimate); the
        # bench harness reads these to compute tokens/sec/chip.
        self.stats = {"tokens": 0, "runs": 0}
        # Telemetry (obs/): bound once; per-response decode stats feed the
        # run-aggregate counters the CLI footer and metrics.json read.
        from llm_consensus_tpu import obs

        self._obs = obs.recorder()
        # Live plane (obs/live, obs/blackbox): per-token latency
        # histograms labeled by priority class for /metricsz, and
        # engine-stream spans (with the request trace id) into the
        # always-on flight recorder ring.
        self._live = obs.live.metrics()
        self._bb = obs.blackbox.ring()
        # Chip-time attribution (obs/attrib): the provider computes LIVE
        # per-pool MFU/MBU gauges from scrape-to-scrape batcher deltas
        # (utilization_stats); the per-site attribution itself lives in
        # the engine/batcher/kv layers.
        self._attrib = obs.attrib.ledger()
        self._util_prev: dict = {}  # preset -> (t, batcher snapshot)
        self._util_last: dict = {}  # preset -> last computed entry
        # One lock for the delta-window state: /statsz pollers and
        # /metricsz scrapers run on separate handler threads, and an
        # unlocked check-then-advance would shrink each other's windows
        # to noise — the exact failure _UTIL_MIN_WINDOW_S exists to stop.
        self._util_lock = sanitizer.make_lock("providers.tpu.util")
        # Crash recovery (recovery/): with stream journaling on
        # (LLMC_JOURNAL), every batched generation routes through an
        # EngineSupervisor — engine death mid-decode becomes a rebuild +
        # journal replay instead of N failed requests. Bound once, like
        # faults/obs: journaling off ⇒ this stays None and the batcher
        # submit path is byte-identical to before.
        from llm_consensus_tpu import recovery

        _journal = recovery.journal()
        self._recovery = (
            recovery.EngineSupervisor(self, _journal)
            if _journal is not None else None
        )
        # Pressure-governor brownout (pressure/): while set, drafted
        # decode routes plain — speculation is a speed lever, and under
        # brownout predictable-degraded beats fast-maybe.
        self._brownout_active = False
        # Disaggregated prefill/decode serving (engine/handoff.py,
        # LLMC_DISAGG / `serve --disagg`): prepare() splits each
        # preset's device slice into disjoint prefill and decode
        # sub-meshes (parallel/mesh.split_roles) and _generate routes
        # admission prefill through a dedicated prefill worker that
        # publishes finished prefix KV into the decode engine's paged
        # pool — admission compute leaves the decode chips. Default off
        # keeps every path byte-identical to the interleaved-admission
        # form; the feature rides the KV pool, so a disagg request
        # without LLMC_KV_POOL=1 degrades (warned once) to classic.
        if disagg is None:
            disagg = knobs.get_bool("LLMC_DISAGG")
        self._disagg_enabled = bool(disagg)
        self._disagg_fraction = knobs.get_float("LLMC_DISAGG_FRACTION")
        # Polled handoff wait (default on): the submitter thread checks
        # its request context between short wait slices instead of one
        # opaque Event.wait, so a cancelled request abandons the ticket
        # within a slice and panel SSE flushes interleave with the wait.
        self._disagg_overlap = knobs.get_bool("LLMC_DISAGG_OVERLAP")
        self._prefill_meshes: dict[str, object] = {}  # preset -> Mesh
        self._handoffs: dict[str, tuple] = {}  # preset -> (engine, KVHandoff|None)
        self._disagg_pool_warned = False

    @property
    def max_batch(self) -> int:
        """Continuous-batcher slots per preset (1 = direct single-stream
        path). The serving gateway validates its admission concurrency
        cap against this at server start."""
        return self._batch_streams

    @classmethod
    def shared(cls) -> "TPUProvider":
        with cls._shared_lock:
            if cls._shared is None:
                cls._shared = cls()
            return cls._shared

    def prepare(
        self, models: list[str], judge: Optional[str], devices=None
    ) -> None:
        """Carve the visible devices into per-model mesh slices.

        Panel models land on disjoint slices so their decode loops never
        contend for chips; the judge — typically the big model — gets the
        larger slice and a TP degree from parallel/mesh.best_tp. A preset
        serving both roles keeps the judge's (larger) slice. Presets whose
        placement changed — or that are absent from the new plan — drop
        their cached engine so stale placements never overlap fresh slices.
        """
        from llm_consensus_tpu.models.config import get_config
        from llm_consensus_tpu.parallel.mesh import plan_panel

        judge_preset = (
            parse_model_name(judge) if judge and judge.startswith(SCHEME) else None
        )
        panel_presets = list(dict.fromkeys(
            parse_model_name(m)
            for m in models
            if m.startswith(SCHEME)
        ))
        if not panel_presets and judge_preset is None:
            return
        with self._lock:
            bad = set(self._bad_devices)
        if bad:
            import jax

            pool = list(devices if devices is not None else jax.devices())
            survivors = [d for d in pool if d.id not in bad]
            if survivors:  # every chip bad: plan as usual, fail honestly
                devices = survivors
        plan = plan_panel(
            [(p, get_config(p)) for p in panel_presets if p != judge_preset],
            (judge_preset, get_config(judge_preset)) if judge_preset else None,
            devices=devices,
            disagg_fraction=(
                self._disagg_fraction if self._disagg_enabled else None
            ),
        )
        def mesh_key(mesh):
            if mesh is None:
                return None
            return (
                tuple(d.id for d in mesh.devices.flat),
                tuple(mesh.axis_names),
                tuple(mesh.devices.shape),
            )

        meshes = {p.model: p.mesh for p in plan.placements}
        prefill_meshes = {
            p.model: p.prefill_mesh for p in plan.placements
        }
        stale_batchers = []
        stale_handoffs = []
        with self._lock:
            for preset, mesh in meshes.items():
                old = self._meshes.get(preset)
                # Same layout keeps the cached engine (weights + compiled
                # programs); only a real placement change forces a rebuild.
                if old is not None and mesh_key(old) == mesh_key(mesh):
                    meshes[preset] = old
                elif preset in self._engines:
                    stale_batchers.append(self._evict_locked(preset))
            # Presets not in the new plan are stale: their slices may now
            # overlap the fresh ones, and their engines (placed or not)
            # pin device memory.
            for preset in list(self._meshes):
                if preset not in meshes:
                    del self._meshes[preset]
            for preset in list(self._engines):
                if preset not in meshes:
                    stale_batchers.append(self._evict_locked(preset))
            # Prefill-role meshes (disaggregation): a changed or dropped
            # prefill slice invalidates that preset's handoff worker —
            # its prefill engine is placed on chips a fresh plan may
            # reassign.
            for preset in set(self._prefill_meshes) | set(prefill_meshes):
                if mesh_key(self._prefill_meshes.get(preset)) != mesh_key(
                    prefill_meshes.get(preset)
                ):
                    ent = self._handoffs.pop(preset, None)
                    if ent is not None:
                        stale_handoffs.append(ent)
            self._prefill_meshes = {
                k: v for k, v in prefill_meshes.items() if v is not None
            }
            self._meshes.update(meshes)
        for entry in stale_batchers:
            if entry is not None:
                entry[1].close()
        for _eng, handoff in stale_handoffs:
            if handoff is not None:
                handoff.close()

    def placement(self, model: str):
        """Mesh the preset serving ``model`` is (or will be) placed on."""
        with self._lock:
            return self._meshes.get(parse_model_name(model))

    def batcher_stats(self) -> dict:
        """Phase-accounting snapshot of every live continuous-batching
        pool, keyed by preset (ContinuousBatcher.snapshot) — what
        metrics.json records as the run's batcher state."""
        with self._lock:
            entries = list(self._batchers.items())
        return {preset: entry[1].snapshot() for preset, entry in entries}

    def kv_stats(self) -> dict:
        """Cross-request paged-KV-pool occupancy + hit/eviction counters
        per preset (kv/pool.KVPool.stats) — the /statsz ``kv`` block and
        metrics.json's pool state. Empty when no live engine runs with
        LLMC_KV_POOL on, so the HTTP surface shape is opt-in like the
        pool itself."""
        with self._lock:
            engines = dict(self._engines)
            for preset, (eng, _batcher) in self._batchers.items():
                engines.setdefault(preset, eng)
        out: dict = {}
        for preset, eng in engines.items():
            pool = getattr(eng, "_kv_pool", None)
            if pool is not None:
                try:
                    out[preset] = pool.stats()
                except Exception:  # noqa: BLE001 — stats must not throw
                    continue
        return out

    def swap_weights(
        self,
        model: str,
        params_or_path,
        version: Optional[int] = None,
        *,
        wait: bool = False,
        meta: Optional[dict] = None,
    ) -> dict:
        """Hot-swap ``model``'s engine onto a new checkpoint (flywheel).

        ``params_or_path`` is either a materialized params pytree or an
        orbax checkpoint path (``<out>/vNNNN/params`` from
        flywheel/distill.py). ``version=None`` auto-increments past the
        resident version. The engine prepares (shards/quantizes) and
        double-buffers per its pin discipline — in-flight streams finish
        on their pinned version; ``wait=True`` blocks up to
        LLMC_SWAP_WAIT_S for the flip. Returns the engine's swap stats
        plus ``accepted``."""
        eng = self._engine_for(model)
        params = params_or_path
        m = dict(meta or {})
        if isinstance(params_or_path, str):
            from llm_consensus_tpu.engine.checkpoint import load_params

            params = load_params(params_or_path)
            m.setdefault("checkpoint", params_or_path)
        from llm_consensus_tpu import faults as _faults
        from llm_consensus_tpu import integrity

        plane = integrity.plane()
        want_digest = m.get("params_digest")
        if plane is not None and isinstance(want_digest, str):
            # Verify the loaded tree against the digest save_checkpoint
            # stamped into version.json BEFORE the engine prepares or
            # installs anything: a checkpoint whose bytes rotted on disk
            # (or a bit_flip@surface=ckpt injection) is refused here —
            # the gateway maps accepted=False onto 409 and
            # latest_checkpoint never advances to it.
            plane.check("ckpt")
            got = integrity.digest_tree(params)
            fplan = _faults.plan()
            if fplan is not None:
                fs = fplan.fire("corrupt", surface="ckpt", model=model)
                if fs is not None and fs.kind == "bit_flip":
                    got = f"{(int(got, 16) ^ 1):08x}"
            if got != want_digest:
                plane.failure(
                    "ckpt",
                    f"params digest mismatch for {model} "
                    f"(want {want_digest}, got {got})",
                )
                out = eng.swap_stats()
                out["accepted"] = False
                out["rejected"] = "params_digest_mismatch"
                return out
        if version is None:
            version = eng.weight_version + 1
        ok = eng.swap_weights(int(version), params, wait=wait, meta=m)
        out = eng.swap_stats()
        out["accepted"] = bool(ok)
        return out

    def rollback_weights(
        self, model: str, meta: Optional[dict] = None
    ) -> Optional[int]:
        """Swap ``model`` back to its previous resident buffer (canary
        rollback); returns the new monotone version or None when there
        is no previous buffer. The engine must already exist — a
        rollback never triggers a lazy build."""
        preset = parse_model_name(model)
        with self._lock:
            eng = self._engines.get(preset)
        if eng is None:
            return None
        return eng.rollback_weights(meta)

    def swap_stats(self) -> dict:
        """Per-preset weight-version + swap counters of every live
        engine (Engine.swap_stats) — the /statsz ``flywheel`` block and
        metrics.json's hot-swap state. Empty until an engine exists."""
        with self._lock:
            engines = dict(self._engines)
            for preset, (eng, _batcher) in self._batchers.items():
                engines.setdefault(preset, eng)
        out: dict = {}
        for preset, eng in engines.items():
            fn = getattr(eng, "swap_stats", None)
            if fn is None:
                continue
            try:
                out[preset] = fn()
            except Exception:  # noqa: BLE001 — stats must not throw
                continue
        return out

    def weight_version(self) -> int:
        """Max resident weight version across live engines — the scalar
        a replica heartbeats to the router (serve/fleet.py) so the
        canary lane can split traffic by version."""
        return max(
            (st.get("weight_version", 0) for st in self.swap_stats().values()),
            default=0,
        )

    def spec_stats(self) -> dict:
        """Speculative-decoding state per preset: single-stream
        SpeculativeEngine cumulative stats and/or the continuous pool's
        spec snapshot (ContinuousBatcher.spec_snapshot) — the /statsz
        ``spec`` block and metrics.json's speculation state. Empty when
        no draft is configured, so the HTTP surface shape is opt-in like
        the feature."""
        with self._lock:
            specs = dict(self._specs)
            batchers = dict(self._batchers)
        out: dict = {}
        for preset, (_eng, spec) in specs.items():
            if spec is None:
                continue
            out[preset] = {
                "kind": spec.drafter.kind,
                "k": spec.k,
                "rounds": spec.stats["rounds"],
                "accepted": spec.stats["accepted"],
                "mean_accepted": round(spec.mean_accepted, 3),
                "accept_ema": round(spec.last_accept_ema, 3),
                "governor_disables": spec.stats["governor_disables"],
                "collapse_faults": spec.stats["collapse_faults"],
            }
        for preset, (_eng, batcher) in batchers.items():
            snap_fn = getattr(batcher, "spec_snapshot", None)
            try:
                snap = snap_fn() if snap_fn is not None else None
            except Exception:  # noqa: BLE001 — stats must not throw
                continue
            if snap:
                out[preset] = snap
        return out

    def _batcher_entries(self) -> list:
        """Live ``(preset, (engine, batcher))`` pairs — the supervisor's
        watchdog iterates this each poll."""
        with self._lock:
            return list(self._batchers.items())

    def utilization_stats(self) -> dict:
        """LIVE per-pool decode utilization: tokens/s, MFU, and MBU over
        the window since the previous WINDOW ADVANCE (deltas of the
        batcher's decode-phase accounting), so ``/metricsz`` carries a
        current gauge instead of a lifetime average — the chip-time
        attribution plane's "live MFU" surface. The window only advances
        after ``_UTIL_MIN_WINDOW_S``; calls inside it replay the last
        computed entry, so concurrent consumers (/statsz pollers +
        /metricsz scrapers share this one delta state) can't shrink each
        other's measurement window to noise. First scrape per pool
        returns only occupancy (no delta yet)."""
        import time as _time

        import jax

        from llm_consensus_tpu.utils.flops import (
            batched_decode_mbu, decode_mfu)

        now = _time.monotonic()
        out: dict = {}
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 — no backend: no gauges
            return out
        for preset, (eng, batcher) in self._batcher_entries():
            try:
                snap = batcher.snapshot()
                live = sum(
                    1 for s in batcher._slots if s is not None
                )
                with self._util_lock:
                    prev = self._util_prev.get(preset)
                    if prev is not None and (
                        now - prev[0] < self._UTIL_MIN_WINDOW_S
                    ):
                        # Inside the minimum window: replay the last
                        # entry (occupancy refreshed — a point read).
                        last = dict(self._util_last.get(preset, {}))
                        last["live_streams"] = live
                        out[preset] = last
                        continue
                    # Claim the window advance under the lock so a
                    # concurrent scrape replays instead of re-advancing.
                    self._util_prev[preset] = (now, snap)
                entry: dict = {"live_streams": live}
                if prev is not None:
                    d_tok = snap["decode_tokens"] - prev[1]["decode_tokens"]
                    d_s = snap["decode_s"] - prev[1]["decode_s"]
                    if d_tok > 0 and d_s > 0:
                        tps = d_tok / d_s
                        n_dev = (
                            eng.mesh.devices.size
                            if eng.mesh is not None else 1
                        )
                        entry["tokens_per_sec"] = round(tps, 2)
                        mfu = decode_mfu(
                            eng.cfg, tps, device_kind, n_devices=n_dev
                        )
                        if mfu is not None:
                            entry["mfu"] = round(mfu, 4)
                        mbu = batched_decode_mbu(
                            eng.cfg, tps, max(1, live), device_kind,
                            n_devices=n_dev,
                            weight_bytes={"int8": 1, "int4": 0.5}.get(
                                eng.quant, 2
                            ),
                            kv_bytes=1 if eng.kv_quant == "int8" else 2,
                        )
                        if mbu is not None:
                            entry["mbu"] = round(mbu, 4)
                    else:
                        entry["tokens_per_sec"] = 0.0
                with self._util_lock:
                    self._util_last[preset] = entry
                out[preset] = entry
            except Exception:  # noqa: BLE001 — stats must not throw
                continue
        # Per-role gauges (disaggregation): the prefill mesh's live
        # token rate + MFU from scrape-to-scrape deltas of the handoff
        # worker's prefill accounting, keyed ``<preset>:prefill`` so
        # /metricsz carries one utilization gauge per ROLE. Prefill
        # flops/token ≈ decode flops/token (2·params; the attention
        # quadratic is second-order at serving prompt lengths), so the
        # decode MFU model serves both roles.
        with self._lock:
            handoffs = dict(self._handoffs)
        for preset, (_eng, handoff) in handoffs.items():
            if handoff is None:
                continue
            try:
                snap = handoff.snapshot()
                key = f"{preset}:prefill"
                with self._util_lock:
                    prev = self._util_prev.get(key)
                    if prev is not None and (
                        now - prev[0] < self._UTIL_MIN_WINDOW_S
                    ):
                        last = dict(self._util_last.get(key, {}))
                        last["queued"] = snap["queued"]
                        out[key] = last
                        continue
                    self._util_prev[key] = (now, snap)
                entry = {"role": "prefill", "queued": snap["queued"]}
                if prev is not None:
                    d_tok = snap["prefill_tokens"] - prev[1]["prefill_tokens"]
                    d_s = snap["prefill_s"] - prev[1]["prefill_s"]
                    if d_tok > 0 and d_s > 0:
                        tps = d_tok / d_s
                        entry["tokens_per_sec"] = round(tps, 2)
                        mfu = decode_mfu(
                            handoff._pe.cfg, tps, device_kind,
                            n_devices=snap["prefill_devices"],
                        )
                        if mfu is not None:
                            entry["mfu"] = round(mfu, 4)
                    else:
                        entry["tokens_per_sec"] = 0.0
                with self._util_lock:
                    self._util_last[key] = entry
                out[key] = entry
            except Exception:  # noqa: BLE001 — stats must not throw
                continue
        return out

    # -- pressure hooks (pressure/governor.py) -------------------------------

    def pressure_stats(self) -> dict:
        """Per-preset batcher headroom (live/cap/queued/preemptions) —
        the governor's batcher-pressure signal and the /statsz
        ``pressure`` block's per-pool detail. Under disaggregation the
        handoff queue's depth folds into ``queued``: a backed-up
        prefill tier is latency already committed, so it backpressures
        the gateway's admission ladder exactly like batcher queueing."""
        with self._lock:
            handoffs = dict(self._handoffs)
        out: dict = {}
        for preset, (_eng, batcher) in self._batcher_entries():
            fn = getattr(batcher, "pressure_snapshot", None)
            if fn is None:
                continue
            try:
                snap = fn()
            except Exception:  # noqa: BLE001 — stats must not throw
                continue
            ent = handoffs.get(preset)
            if ent is not None and ent[1] is not None:
                try:
                    hq = ent[1].queued()
                except Exception:  # noqa: BLE001
                    hq = 0
                if hq:
                    snap = dict(snap)
                    snap["handoff_queued"] = hq
                    snap["queued"] = snap.get("queued", 0) + hq
            out[preset] = snap
        return out

    def request_preempt(self, max_victims: int = 1) -> None:
        """Governor ``preempt`` rung: nudge every live pool to preempt
        its lowest-priority streams for blocked higher-priority admits.
        Each batcher verifies the predicate itself — an unjustified
        nudge is a no-op."""
        for _preset, (_eng, batcher) in self._batcher_entries():
            fn = getattr(batcher, "preempt", None)
            if fn is not None:
                try:
                    fn(max_victims)
                except Exception:  # noqa: BLE001 — best-effort
                    continue

    def set_brownout(self, on: bool) -> None:
        """Governor ``brownout`` rung: route drafted decode plain for
        the duration — single-stream speculation bypassed, pooled spec
        mode forced to its plain window. Speed levers off; the plain
        paths are always correct."""
        self._brownout_active = bool(on)
        for _preset, (_eng, batcher) in self._batcher_entries():
            fn = getattr(batcher, "set_brownout", None)
            if fn is not None:
                try:
                    fn(on)
                except Exception:  # noqa: BLE001
                    continue

    def kv_evict_cold(self, target_occupancy: float) -> int:
        """Governor ``evict`` rung: drop cold KV-pool blocks down to the
        target occupancy across every live engine's pool. Returns blocks
        freed."""
        with self._lock:
            engines = dict(self._engines)
            for preset, (eng, _batcher) in self._batchers.items():
                engines.setdefault(preset, eng)
        freed = 0
        for eng in engines.values():
            pool = getattr(eng, "_kv_pool", None)
            if pool is None:
                continue
            try:
                freed += pool.evict_cold(target_occupancy)
            except Exception:  # noqa: BLE001
                continue
        return freed

    def recovery_stats(self) -> dict:
        """Engine-liveness + recovery state for /healthz and /statsz:
        per-pool decode-heartbeat ages, the worst age among BUSY pools
        (idle pools legitimately stop beating), and — when supervision is
        on — restart/replay counters and journal depth."""
        hearts: dict = {}
        worst = None
        for preset, (_eng, batcher) in self._batcher_entries():
            try:
                busy = batcher.busy()
                age = round(batcher.heartbeat_age(), 3)
            except Exception:  # noqa: BLE001 — liveness must not throw
                continue
            hearts[preset] = {"age_s": age, "busy": busy}
            if busy and (worst is None or age > worst):
                worst = age
        out: dict = {
            "state": "ok",
            "restarts": 0,
            "replayed_streams": 0,
            "journal_depth": 0,
            "heartbeats": hearts,
            "decode_heartbeat_age_s": worst,
        }
        if self._recovery is not None:
            sup = self._recovery.stats()
            out["state"] = sup["state"]
            out["restarts"] = sup["restarts"]
            out["replayed_streams"] = sup["replayed_streams"]
            out["journal_depth"] = sup["journal"]["depth"]
            out["heartbeat_s"] = sup["heartbeat_s"]
        return out

    def set_draft(self, spec: str, k: Optional[int] = None) -> None:
        """Re-configure speculative drafting (``--draft`` / ``--spec-k``
        on the shared provider). Cached pairs drop so the new map applies
        immediately; target engines stay warm. Live BATCHERS keep their
        construction-time spec mode — the pool's programs are compiled
        state; a changed map applies to pools built after this call.
        ``k=None`` RESETS to the env default rather than keeping the
        previous call's value: these flags are plumbed per run exactly so
        one in-process run's settings can't leak into the next."""
        with self._lock:
            self._draft_map = _parse_draft_spec(spec)
            self._spec_k = max(
                1, k if k is not None else knobs.get_int("LLMC_SPEC_K")
            )
            self._specs.clear()

    def set_spec_k(self, k: int) -> None:
        """Set only the draft-length ceiling, keeping the current draft
        map (``serve --spec-k`` without ``--draft`` must not wipe an
        env-configured LLMC_DRAFT)."""
        with self._lock:
            self._spec_k = max(1, k)
            self._specs.clear()

    def release(self) -> None:
        """Drop every engine, batcher, and placement this provider holds.

        Engines pin weights, KV caches, prefix snapshots, and compiled
        programs in HBM; a caller that is done serving (shutdown, or a
        bench handing the chip to another provider) frees that memory
        deterministically instead of waiting on GC. The provider remains
        usable — the next query lazily rebuilds (unplaced) engines.
        """
        with self._lock:
            batchers = list(self._batchers.values())
            handoffs = list(self._handoffs.values())
            self._batchers.clear()
            self._engines.clear()
            self._meshes.clear()
            self._specs.clear()
            self._handoffs.clear()
            self._prefill_meshes.clear()
        for _eng, handoff in handoffs:
            if handoff is not None:
                handoff.close()
        for _, batcher in batchers:
            batcher.close()

    def _engine_for(self, model: str):
        """Get or lazily create the engine serving ``model``.

        Engine construction (weight init / checkpoint load) happens outside
        the pool lock under a per-preset lock, so distinct panel models
        build concurrently while duplicate requests for one model share a
        single build.
        """
        preset = parse_model_name(model)
        with self._lock:
            engine = self._engines.get(preset)
            if engine is not None:
                return engine
            build_lock = self._build_locks.setdefault(
                preset, sanitizer.make_lock("providers.tpu.build")
            )
        with build_lock:
            while True:
                with self._lock:
                    engine = self._engines.get(preset)
                    if engine is not None:
                        return engine
                    mesh = self._meshes.get(preset)
                engine = self._build_engine(preset, mesh)
                with self._lock:
                    # A concurrent prepare() may have re-planned while this
                    # build ran; cache only an engine whose placement is
                    # still current, else rebuild on the new mesh.
                    if self._meshes.get(preset) is mesh:
                        self._engines[preset] = engine
                        return engine

    def _build_engine(self, preset: str, mesh=None, kv_pool: bool = True):
        from llm_consensus_tpu import faults
        from llm_consensus_tpu.engine import Engine
        from llm_consensus_tpu.engine.checkpoint import try_load_params
        from llm_consensus_tpu.engine.tokenizer import load_tokenizer
        from llm_consensus_tpu.models.config import get_config

        fault_plan = faults.plan()
        if fault_plan is not None:
            # build_fail[@preset=name]: the construction itself dies (a
            # wedged chip failing the param allocation) — exercises the
            # evict→rebuild→re-place ladder in query_stream, which treats
            # a failed REBUILD as evidence the placement is suspect.
            fault_plan.check("build", preset=preset)

        _enable_compilation_cache()

        cfg = get_config(preset)
        params = None
        tokenizer = None
        if self._checkpoint_dir:
            ckpt = os.path.join(self._checkpoint_dir, preset)
            # Multi-device placements restore straight into their TP
            # shardings (no full-param materialization — the 70B judge
            # cannot load any other way).
            params = try_load_params(cfg, ckpt, mesh=mesh)
            tokenizer = load_tokenizer(ckpt)
        max_seq = (
            min(self._max_seq, cfg.max_seq_len) if self._max_seq else None
        )
        return Engine(
            cfg, params, tokenizer=tokenizer, mesh=mesh, max_seq=max_seq,
            stream_interval=self._stream_interval, quant=self._quant,
            kv_quant=self._kv_quant, kv_pool=kv_pool,
        )

    def _evict_locked(self, preset: str, engine=None):
        """Under ``self._lock``: drop ``preset``'s cached engine/batcher/
        spec/handoff entries; with ``engine``, only state belonging to
        that engine generation (a concurrent retry may already have
        published a healthy replacement). Returns the batcher the CALLER
        must close outside the lock (its scheduler thread takes the same
        lock); the popped handoff (if any) is closed inline — close()
        only flips a flag and fails queued tickets."""
        if engine is None or self._engines.get(preset) is engine:
            self._engines.pop(preset, None)
        self._specs.pop(preset, None)
        hstale = self._handoffs.get(preset)
        if hstale is not None and (engine is None or hstale[0] is engine):
            self._handoffs.pop(preset)
            if hstale[1] is not None:
                hstale[1].close()
        stale = self._batchers.get(preset)
        if stale is not None and (engine is None or stale[0] is engine):
            self._batchers.pop(preset)
            return stale
        return None

    def _evict(self, preset: str, engine=None) -> None:
        with self._lock:
            stale = self._evict_locked(preset, engine)
        if stale is not None:
            stale[1].close()

    def _replace_engine(self, preset: str, failed_ids: set):
        """Elastic re-placement: move ``preset`` off a twice-failed slice
        onto spare healthy chips, returning the fresh engine (or None when
        no healthy chips remain).

        The device-level analog of the reference's failure isolation
        (runner.go:100-107): one dead slice must cost a re-plan, not the
        model. Preference order for the new home: local chips no placement
        is using (true spares), else healthy chips another model occupies
        (time-multiplexed — slower beats failed). Only THIS process's
        addressable devices are candidates: under multi-controller
        execution another host's chips cannot be driven from here, and
        staying on the owner's host keeps every other process's ownership
        routing (min process_index over the old mesh) valid. The failed
        devices are remembered so later prepare() re-plans route around
        them instead of placing the model straight back on a wedged chip.
        """
        import warnings

        import jax

        from llm_consensus_tpu.models.config import get_config
        from llm_consensus_tpu.parallel.mesh import (
            _pow2_floor, best_tp, host_groups, make_mesh)

        with self._lock:
            self._bad_devices.update(failed_ids)
            exclude = set(self._bad_devices)  # every chip EVER seen wedged
            used = {
                d.id
                for p, m in self._meshes.items()
                if p != preset
                for d in m.devices.flat
            }
        healthy = [d for d in jax.local_devices() if d.id not in exclude]
        if not healthy:
            return None
        spare = [d for d in healthy if d.id not in used]
        pool = spare if spare else healthy
        group = max(host_groups(pool), key=len)
        cfg = get_config(preset)
        n = _pow2_floor(len(group))
        tp = best_tp(cfg, n)
        mesh = make_mesh({"dp": 1, "tp": tp}, group[:tp])
        warnings.warn(
            f"re-placing {preset} after repeated failures on devices "
            f"{sorted(failed_ids)} -> {sorted(d.id for d in mesh.devices.flat)}"
            + ("" if spare else " (sharing a healthy model's slice)"),
            RuntimeWarning,
            stacklevel=2,
        )
        with self._lock:
            self._meshes[preset] = mesh
        self._evict(preset)
        return self._engine_for(preset)

    def _handoff_for(self, preset: str, engine):
        """The live KVHandoff serving ``preset``'s decode engine, lazily
        built, or None when disaggregation can't attach (no prefill
        mesh planned — the slice was too small to split — or the decode
        engine runs without the paged KV pool, which IS the handoff
        channel). A build failure disables the handoff for this engine
        generation with one warning: disaggregation only ever changes
        where prefill compute runs, so the classic interleaved path is
        always a correct fallback."""
        if not self._disagg_enabled:
            return None
        with self._lock:
            ent = self._handoffs.get(preset)
            if ent is not None and ent[0] is engine:
                return ent[1]
            pmesh = self._prefill_meshes.get(preset)
        if pmesh is None:
            return None
        if getattr(engine, "_kv_pool", None) is None:
            if not self._disagg_pool_warned:
                self._disagg_pool_warned = True
                import warnings

                warnings.warn(
                    "LLMC_DISAGG requested but the decode engine has no "
                    "paged KV pool (set LLMC_KV_POOL=1): running the "
                    "classic interleaved-admission path",
                    RuntimeWarning,
                    stacklevel=2,
                )
            with self._lock:
                self._handoffs.setdefault(preset, (engine, None))
            return None
        with self._lock:
            build_lock = self._build_locks.setdefault(
                ("handoff", preset), sanitizer.make_lock("providers.tpu.build.handoff")
            )
        with build_lock:
            with self._lock:
                ent = self._handoffs.get(preset)
                if ent is not None and ent[0] is engine:
                    return ent[1]
            stale = ent[1] if ent is not None else None
            try:
                from llm_consensus_tpu.engine.handoff import KVHandoff

                # kv_pool=False: the prefill-only engine publishes into
                # the DECODE engine's pool — a second same-preset arena
                # would be dead weight and collide on the watermark
                # component key (classic snapshot reuse still serves
                # its shared-prefix waves).
                prefill_engine = self._build_engine(
                    preset, mesh=pmesh, kv_pool=False
                )
                handoff = KVHandoff(prefill_engine, engine, name=preset)
            except Exception as exc:  # noqa: BLE001 — classic fallback
                import warnings

                warnings.warn(
                    f"disaggregated prefill disabled for {preset}: {exc}",
                    RuntimeWarning,
                    stacklevel=2,
                )
                handoff = None
            with self._lock:
                self._handoffs[preset] = (engine, handoff)
            if stale is not None:
                stale.close()
            return handoff

    def disagg_stats(self) -> dict:
        """Per-preset handoff state (queue depth, waves, transfer
        bytes/s, fallbacks, per-role device counts) — the /statsz
        ``disagg`` block and metrics.json's disaggregation view. Empty
        when disaggregation is off or no handoff is live, so the HTTP
        surface shape is opt-in like the feature."""
        with self._lock:
            handoffs = dict(self._handoffs)
        out: dict = {}
        for preset, (_eng, handoff) in handoffs.items():
            if handoff is None:
                continue
            try:
                out[preset] = handoff.snapshot()
            except Exception:  # noqa: BLE001 — stats must not throw
                continue
        return out

    def seal_stream(self, trace_id, model=None):
        """Seal the open journal entry for the stream carrying
        ``trace_id`` and return its migration resume payload —
        ``{"prompt_ids", "sampling", "tokens"}`` — the authoritative
        frontier a destination replica replays through ``submit_ids``
        (serve/elastic.py's journal-backed live migration).

        ``seal`` freezes the entry, so decode chunks a still-running
        worker appends AFTER this call are dropped from the snapshot
        and regenerated deterministically by the resume — the exact
        contract crash replay relies on. Returns None when the journal
        is off, no open entry matches, or the match is ambiguous (a
        multi-model panel shares one trace id and entries do not record
        the model): the gateway then ships the emitted-text payload,
        which deterministic re-decode plus the router's ledger burn
        still resumes byte-identically."""
        if not trace_id:
            return None
        from llm_consensus_tpu import recovery as recovery_mod
        from llm_consensus_tpu.recovery.journal import _sampling_dict

        journal = recovery_mod.journal()
        if journal is None:
            return None
        matches = [
            e for e in journal.active()
            if e.trace == trace_id and e.finish is None
        ]
        if len(matches) != 1:
            return None
        entry = matches[0]
        tokens = entry.seal()
        return {
            "prompt_ids": list(entry.prompt_ids),
            "sampling": _sampling_dict(entry.sampling),
            "tokens": list(tokens),
        }

    def replan_disagg(self, preset: str, fraction: float) -> dict:
        """Re-carve ``preset``'s prefill share at runtime (the elastic
        tier's re-planning hook): recompute ``split_roles`` over the
        union of the preset's current decode + prefill devices with the
        new fraction and republish the prefill mesh. The decode mesh —
        the resident pool and every compiled decode program — never
        moves: only where prefill compute runs changes, which is
        disaggregation's correctness envelope. Serialized under the
        same per-preset handoff build lock ``_handoff_for`` uses, so a
        re-carve never races a handoff build; the stale worker closes
        and the next request lazily rebuilds on the new slice. Device
        time spent here books to the ``elastic`` attribution family."""
        from llm_consensus_tpu.models.config import get_config
        from llm_consensus_tpu.obs.attrib import tag as attrib_tag
        from llm_consensus_tpu.parallel.mesh import split_roles

        f = min(max(float(fraction), 0.05), 0.9)
        with self._lock:
            build_lock = self._build_locks.setdefault(
                ("handoff", preset),
                sanitizer.make_lock("providers.tpu.build.handoff"),
            )
        with build_lock, attrib_tag("elastic"):
            with self._lock:
                self._disagg_fraction = f
                dmesh = self._meshes.get(preset)
                pmesh = self._prefill_meshes.get(preset)
            if dmesh is None or not self._disagg_enabled:
                # Nothing placed (or disagg off): the new fraction still
                # sticks for the next prepare()-time plan.
                return {"preset": preset, "fraction": f, "changed": False}
            seen: dict = {}
            for m in (dmesh, pmesh):
                if m is None:
                    continue
                for d in m.devices.flat:
                    seen.setdefault(d.id, d)
            pool = [seen[i] for i in sorted(seen)]
            new_pmesh, _ = split_roles(
                get_config(preset), pool, prefill_fraction=f
            )

            def key(m):
                return (
                    None if m is None
                    else tuple(d.id for d in m.devices.flat)
                )

            changed = key(new_pmesh) != key(pmesh)
            stale = None
            if changed:
                with self._lock:
                    if new_pmesh is None:
                        self._prefill_meshes.pop(preset, None)
                    else:
                        self._prefill_meshes[preset] = new_pmesh
                    stale = self._handoffs.pop(preset, None)
            if stale is not None and stale[1] is not None:
                stale[1].close()
            if self._obs is not None:
                self._obs.count("elastic.recarves")
                self._obs.instant(
                    "disagg_recarve", tid="provider", preset=preset,
                    fraction=f, changed=changed,
                )
            return {
                "preset": preset,
                "fraction": f,
                "changed": changed,
                "prefill_devices": (
                    [] if new_pmesh is None
                    else [d.id for d in new_pmesh.devices.flat]
                ),
                "decode_devices": [d.id for d in dmesh.devices.flat],
            }

    def _draft_preset_for(self, preset: str) -> Optional[str]:
        draft = self._draft_map.get(preset, self._draft_map.get("*"))
        return draft if draft and draft != preset else None

    def _spec_config_for(self, preset: str):
        """SpecConfig for ``preset``'s continuous-batching pool, or None.

        Only BUFFER drafters batch (``--draft lookup``): the pool's spec
        mode proposes from its device token buffer, so there is no
        second cache to co-locate and rounds pipeline across every
        resident row. Model drafts stay single-stream."""
        if self._draft_preset_for(preset) != "lookup":
            return None
        from llm_consensus_tpu.engine.speculative import spec_config_from_env

        # Construction-time ngram (like k): the single-stream drafter and
        # the pool must draft with the same gram length even if the env
        # changes between provider build and first pool build.
        return spec_config_from_env(
            kind="lookup", k=self._spec_k, ngram=self._spec_ngram,
        )

    def _spec_for(self, preset: str, engine):
        """Get or build the SpeculativeEngine serving ``preset``, or None
        when no draft is configured / speculation can't attach.

        The pair is cached per (preset, engine identity) — a re-planned
        or rebuilt target drops its stale pair. Build failures (unknown
        draft preset, multi-device target mesh) disable speculation for
        that engine with one warning instead of failing the request: the
        draft only ever changes speed, so the plain path is always a
        correct fallback.
        """
        draft_preset = self._draft_preset_for(preset)
        if draft_preset is None:
            return None
        with self._lock:
            entry = self._specs.get(preset)
            if entry is not None and entry[0] is engine:
                return entry[1]
        try:
            from llm_consensus_tpu.engine.speculative import (
                PromptLookupDrafter, SpeculativeEngine)

            if draft_preset == "lookup":
                # Prompt-lookup drafter: no second model, no co-location
                # constraint (buffer drafters carry no draft cache — a
                # tp-sharded target verifies through plain XLA forwards
                # GSPMD partitions).
                spec = SpeculativeEngine(
                    engine, PromptLookupDrafter(self._spec_ngram),
                    k=self._spec_k,
                )
            else:
                if engine.mesh is not None and engine.mesh.devices.size > 1:
                    # Same predicate SpeculativeEngine applies — checked
                    # BEFORE the draft build so a target speculation
                    # can't attach to never pays a draft's weight load.
                    raise ValueError(
                        "target is placed on a multi-device mesh "
                        "(speculation needs co-located caches; unsharded "
                        "or single-device placements only)"
                    )
                draft_engine = self._build_engine(
                    draft_preset, mesh=engine.mesh
                )
                spec = SpeculativeEngine(engine, draft_engine, k=self._spec_k)
        except Exception as exc:
            import warnings

            warnings.warn(
                f"speculative decoding disabled for {preset} "
                f"(draft {draft_preset}): {exc}",
                RuntimeWarning,
                stacklevel=2,
            )
            spec = None
        with self._lock:
            # Double-checked publish; keep the loser's draft collectible.
            entry = self._specs.get(preset)
            if entry is not None and entry[0] is engine:
                return entry[1]
            self._specs[preset] = (engine, spec)
        return spec

    def _generate(self, engine, preset: str, prompt, sampling, ctx, cb,
                  priority: int = 1, trace_id=None, resume=None):
        """One generation — speculative when a draft is attached, else
        through the shared ContinuousBatcher when stream batching is on
        and the engine is batchable, else the direct single-stream path.

        Batchable = unsharded, or placed on a mesh whose only non-trivial
        axis is ``tp``: the batcher's splice/compact touch only the
        slot/position axes, which TP never shards, so GSPMD partitions
        the whole admission/decode path (validated under a tp mesh in
        tests/test_continuous_batching.py) — this is the TP-sharded
        judge's batched-serving path. Meshes with live sp/pp/dp axes
        stay single-stream (ring prefill admission and stage hand-off
        under a shared-frontier pool are unvalidated).
        """
        draft_preset = self._draft_preset_for(preset)
        if draft_preset is not None:
            if self._batch_streams > 1 and draft_preset == "lookup":
                # The prompt-lookup drafter composes with continuous
                # batching: the pool itself runs spec ROUNDS (batched
                # verification — ContinuousBatcher's spec mode, built
                # from _spec_config_for below). Fall through to the
                # batcher path.
                pass
            elif self._batch_streams > 1:
                # MODEL-drafted speculation (a latency lever: one
                # stream, a private draft cache) and stream batching (a
                # throughput lever: shared-frontier slots) do not
                # compose — a drafted request would bypass the batcher
                # SILENTLY (the exact round-2 VERDICT finding). A
                # serving deployment that configures both gets batching,
                # and is told once; `--draft lookup` is the form that
                # batches.
                if not getattr(self, "_spec_batch_warned", False):
                    self._spec_batch_warned = True
                    import warnings

                    warnings.warn(
                        f"model draft configured for {preset!r} is "
                        "ignored because stream batching is enabled "
                        f"(batch_streams={self._batch_streams}); use "
                        "--draft lookup for speculation that composes "
                        "with continuous batching",
                        RuntimeWarning,
                        stacklevel=2,
                    )
            elif self._brownout_active:
                # Pressure brownout: drafting off — fall through to the
                # plain single-stream path below.
                pass
            elif sampling.temperature == 0.0 or (
                sampling.top_k is None and sampling.top_p is None
            ):
                # Greedy (token-exact) and pure-temperature sampling
                # (distribution-exact via rejection sampling) both ride
                # the draft; top-k/top-p shapes would bounce off the
                # spec engine's internal fallback, so route them plain.
                spec = self._spec_for(preset, engine)
                if spec is not None:
                    return spec.generate(prompt, sampling, ctx, on_text=cb)
        if self._batch_streams <= 1:
            return engine.generate(prompt, sampling, ctx, on_text=cb)
        if engine.mesh is not None:
            sizes = dict(engine.mesh.shape)
            sizes.pop("tp", None)
            if any(v > 1 for v in sizes.values()):
                return engine.generate(prompt, sampling, ctx, on_text=cb)
        from concurrent.futures import CancelledError

        entry = self._batcher_for(preset, engine)
        if entry is None:
            return engine.generate(prompt, sampling, ctx, on_text=cb)
        if resume:
            # Live-migration resume (serve/elastic.py): the retiring
            # replica's sealed journal snapshot rides the SAME replay
            # contract crash recovery uses — the emitted prefix becomes
            # prefill context (re-established, never re-decoded) and
            # re-feeds through on_text, where the router's stream ledger
            # burns the duplicate bytes, so the stream continues
            # byte-identically from the migrated frontier. Handoff is
            # skipped (the replay prefix IS the prefill) and this
            # incarnation forgoes supervisor replay — a pool death
            # mid-resume surfaces like any unsupervised failure. A
            # text-only payload falls through: deterministic decode
            # re-derives the prefix and the ledger still burns it.
            pids = resume.get("prompt_ids")
            toks = resume.get("tokens")
            if pids and toks:
                if self._obs is not None:
                    self._obs.count("elastic.resumes")
                    self._obs.instant(
                        "migrate_resume", tid="provider", preset=preset,
                        trace=trace_id, replayed=len(toks),
                    )
                try:
                    fut = entry[1].submit_ids(
                        list(pids), sampling, ctx=ctx, on_text=cb,
                        replay_ids=tuple(toks), priority=priority,
                        trace_id=trace_id,
                    )
                    return fut.result()
                except (Cancelled, DeadlineExceeded):
                    raise
                except (CancelledError, Exception):  # noqa: BLE001 — re-decode
                    return engine.generate(prompt, sampling, ctx, on_text=cb)
        handoff_trunc = False
        hand_ids = None
        hand_tr = False
        if self._disagg_enabled:
            # Disaggregated admission (engine/handoff.py): establish the
            # prompt's KV on the prefill mesh and publish it into the
            # decode pool BEFORE the submit, so the decode batcher's
            # admission degenerates to a radix gather + suffix install.
            # Every failure mode (no handoff, queue full, stall timeout,
            # worker crash) just falls through to the classic path —
            # disaggregation moves prefill compute, never correctness.
            # The budgeted ids are kept for the submit below, so the
            # prompt tokenizes ONCE on this hot path.
            handoff = self._handoff_for(preset, engine)
            if handoff is not None:
                try:
                    hand_ids, hand_tr = engine._budget_prompt(
                        engine.tokenizer.encode(prompt),
                        sampling.max_new_tokens,
                    )
                    if self._disagg_overlap:
                        _off, handoff_trunc = handoff.run_overlapped(
                            hand_ids, priority=priority, ctx=ctx
                        )
                    else:
                        _off, handoff_trunc = handoff.run(
                            hand_ids, priority=priority, ctx=ctx
                        )
                except (Cancelled, DeadlineExceeded):
                    raise
                except Exception:  # noqa: BLE001 — classic fallback
                    hand_ids = None

        def _with_handoff_kv(result):
            # PR 9's per-response kv block must reflect the HANDOFF
            # path's publish exhaustion exactly like a local retain's:
            # a truncated cross-mesh publish degrades THIS context's
            # reuse even though the decode-side pool never truncated.
            if handoff_trunc:
                result.kv_truncated = True
            return result

        if self._recovery is not None:
            # Supervised path (recovery/): journaled submit; pool death
            # mid-decode becomes rebuild + replay instead of a failed
            # request. The supervisor owns the fallback ladder the
            # unsupervised path below implements inline.
            return _with_handoff_kv(self._recovery.run_stream(
                preset, entry, prompt, sampling, ctx, cb,
                priority=priority, trace_id=trace_id,
            ))
        try:
            if hand_ids is not None:
                # Re-use the handoff path's budgeted ids — same encode +
                # budget the text submit would redo (submit() is just
                # this pair + submit_ids).
                fut = entry[1].submit_ids(
                    hand_ids, sampling, ctx=ctx, on_text=cb,
                    truncated=hand_tr, priority=priority,
                    trace_id=trace_id,
                )
            else:
                fut = entry[1].submit(
                    prompt, sampling, ctx, on_text=cb, priority=priority,
                    trace_id=trace_id,
                )
        except (RuntimeError, ValueError):
            # Closed batcher (shutdown race) or a sampling shape this
            # batcher's compiled program can't serve: direct path.
            return _with_handoff_kv(
                engine.generate(prompt, sampling, ctx, on_text=cb)
            )
        try:
            return _with_handoff_kv(fut.result())
        except CancelledError:
            # A concurrent close() (re-plan, shutdown) cancelled the
            # queued submission — a benign race, not an engine failure;
            # real generation failures propagate to the retry machinery.
            return _with_handoff_kv(
                engine.generate(prompt, sampling, ctx, on_text=cb)
            )

    def _batcher_for(self, preset: str, engine):
        """The live ``(engine, batcher)`` entry serving ``preset`` for
        this engine generation, building it if needed; None when the
        engine was evicted mid-build (caller goes single-stream).

        Build OUTSIDE the pool lock (concurrent queries for OTHER models
        must not serialize behind a cache allocation) but UNDER a
        per-preset build lock: a same-instant burst of B requests
        otherwise races B threads through the old double-checked publish,
        each allocating a full max_batch KV cache before all but one
        loses — measured 34 GB of doomed caches (and an OOM) from a
        32-stream burst.
        """
        from llm_consensus_tpu.engine import ContinuousBatcher

        stale = None
        with self._lock:
            entry = self._batchers.get(preset)
            if entry is not None and entry[0] is not engine:
                # A batcher for a different (older) engine generation.
                self._batchers.pop(preset)
                stale, entry = entry[1], None
            current = self._engines.get(preset) is engine
        if stale is not None:
            stale.close()
        if entry is None and current:
            with self._lock:
                build_lock = self._build_locks.setdefault(
                    ("batcher", preset), sanitizer.make_lock("providers.tpu.build.batcher")
                )
            with build_lock:
                with self._lock:
                    entry = self._batchers.get(preset)
                    stale = None
                    if entry is not None and entry[0] is not engine:
                        self._batchers.pop(preset)
                        stale, entry = entry[1], None
                    current = self._engines.get(preset) is engine
                if stale is not None:
                    stale.close()
                if entry is None and current:
                    batcher = ContinuousBatcher(
                        engine, max_batch=self._batch_streams,
                        prefill_budget=self._prefill_budget,
                        spec=self._spec_config_for(preset),
                    )
                    publish = None
                    with self._lock:
                        if self._engines.get(preset) is engine:
                            self._batchers[preset] = entry = (engine, batcher)
                        else:
                            # prepare() evicted this engine while we
                            # built: a fresh batcher would pin a stale
                            # placement's HBM.
                            publish = batcher
                    if publish is not None:
                        publish.close()
        return entry

    def _recover_batcher(self, preset: str, failed_batcher):
        """Tear down a dead pool and rebuild engine + batcher — the
        supervisor's restart path, serialized per preset so a pool's
        worth of concurrent stream failures costs ONE rebuild.

        The dead batcher is abandoned, never joined: its threads may be
        wedged inside device code (the reason it is being replaced), and
        close()'s 120 s join would stall every replay behind it. Its KV
        cache stays allocated until those daemon threads exit — the same
        trade close() warns about — which is why the fresh engine build
        goes through the normal construction path where allocation
        failures surface honestly. Returns the fresh (engine, batcher).
        """
        with self._lock:
            recover_lock = self._build_locks.setdefault(
                ("recover", preset), sanitizer.make_lock("providers.tpu.build.recover")
            )
        with recover_lock:
            with self._lock:
                entry = self._batchers.get(preset)
            if (
                entry is not None
                and entry[1] is not failed_batcher
                and entry[1].failed_exc is None
            ):
                # A concurrent recovery already published a healthy pool:
                # this waiter replays onto it, no second rebuild.
                return entry
            failed_engine = entry[0] if entry is not None else None
            with self._lock:
                if self._batchers.get(preset) is entry and entry is not None:
                    self._batchers.pop(preset, None)
                if (
                    failed_engine is not None
                    and self._engines.get(preset) is failed_engine
                ):
                    self._engines.pop(preset, None)
                self._specs.pop(preset, None)
            failed_batcher.abandon(RuntimeError(
                f"engine pool for {preset!r} torn down for recovery"
            ))
            engine = self._engine_for(preset)
            entry = self._batcher_for(preset, engine)
            if entry is None:
                raise RuntimeError(
                    f"recovery could not rebuild the {preset!r} pool "
                    "(placement changed mid-recovery)"
                )
            if self._recovery is not None:
                self._recovery.note_restart(preset)
            return entry

    # -- Provider interface --------------------------------------------------

    def query(self, ctx: Context, req: Request) -> Response:
        return self.query_stream(ctx, req, None)

    def query_stream(
        self, ctx: Context, req: Request, callback: Optional[StreamCallback]
    ) -> Response:
        from llm_consensus_tpu.engine import SamplingParams

        try:
            engine = self._engine_for(req.model)
        except (Cancelled, DeadlineExceeded, ValueError):
            raise  # cooperative cancel / deterministic input errors
        except Exception:
            # A transient construction failure (allocation race, a wedged
            # chip dying mid-build, an injected build_fail) gets the same
            # one-rebuild grace the generate path below has — nothing was
            # cached, so retrying is just building again.
            ctx.raise_if_done()
            engine = self._engine_for(req.model)
        start = time.monotonic()
        t0_ns = (
            time.monotonic_ns()
            if self._obs is not None or self._bb is not None else 0
        )
        sampling = SamplingParams(
            max_new_tokens=(
                req.max_tokens if req.max_tokens is not None else DEFAULT_MAX_NEW_TOKENS
            ),
            temperature=req.temperature if req.temperature is not None else 0.0,
            ignore_eos=self._ignore_eos,
        )
        prompt = req.prompt
        if req.system:
            # The plain engine has no chat template; fold the system
            # prompt ahead of the user prompt.
            prompt = f"{req.system}\n\n{req.prompt}"
        streamed = {"n": 0}
        cb = callback
        if callback is not None:
            def cb(chunk, _callback=callback):
                streamed["n"] += 1
                _callback(chunk)
        # Elastic recovery: a transient on-device failure (OOM from HBM
        # fragmentation, a wedged compile, a dropped device link) gets ONE
        # fresh engine before the model is declared failed (best-effort
        # semantics, runner.go:100-107). Retries only if nothing streamed
        # yet — text already on the user's screen must not repeat — and
        # the rebuild happens OUTSIDE the except block so the failed
        # engine (params, prefix snapshot, compiled-program refs, the
        # traceback frames pinning it) is actually collectible before the
        # replacement allocates.
        preset = parse_model_name(req.model)
        # Priority class rides the whole path: batcher admission order,
        # preemption victim selection. None = NORMAL (pressure/priority).
        priority = req.priority if req.priority is not None else 1
        retry = False
        try:
            result = self._generate(
                engine, preset, prompt, sampling, ctx, cb, priority=priority,
                trace_id=req.trace_id, resume=req.resume,
            )
        except (Cancelled, DeadlineExceeded, ValueError):
            raise  # cooperative cancel / deterministic input errors
        except Exception:
            if streamed["n"]:
                raise
            retry = True
        if retry:
            ctx.raise_if_done()  # never pay a rebuild for a doomed request
            failed_ids = {
                d.id for d in getattr(engine, "mesh", None).devices.flat
            } if getattr(engine, "mesh", None) is not None else set()
            self._evict(preset, engine)
            engine = None  # drop the last live reference before rebuilding
            try:
                engine = self._engine_for(req.model)
                result = self._generate(
                    engine, preset, prompt, sampling, ctx, cb,
                    priority=priority, trace_id=req.trace_id,
                    resume=req.resume,
                )
            except (Cancelled, DeadlineExceeded, ValueError):
                raise
            except Exception:
                # Second failure — a generate on the rebuilt engine, or
                # the rebuild itself dying on the dead slice (param
                # allocation on a wedged chip): the placement is suspect,
                # not the transient states one rebuild cures. Re-place
                # the model on spare healthy chips and try once more; no
                # spares or an unplaced engine means the model is
                # genuinely failed (best-effort: a warning upstream,
                # runner.go:100-107). A concurrent prepare() may have
                # re-planned between the two attempts, so the second
                # engine's devices join the exclusion set.
                second_mesh = getattr(engine, "mesh", None)
                if second_mesh is not None:
                    failed_ids |= {d.id for d in second_mesh.devices.flat}
                if streamed["n"] or not failed_ids:
                    raise
                ctx.raise_if_done()
                engine = None
                engine = self._replace_engine(preset, failed_ids)
                if engine is None:
                    raise
                result = self._generate(
                    engine, preset, prompt, sampling, ctx, cb,
                    priority=priority, trace_id=req.trace_id,
                    resume=req.resume,
                )
        with self._lock:
            self.stats["tokens"] += len(result.token_ids)
            self.stats["runs"] += 1
        if result.finish_reason in ("deadline", "cancelled"):
            # Reference parity: a timed-out model is a failed model, not a
            # partial success (runner.go:65, best-effort accounting).
            ctx.raise_if_done()

        # Real decode throughput + MFU (utils/flops.py) from the engine's
        # steady-state fetch-boundary clock; None when the run was too short
        # to measure (single chunk) — short runs would report noise.
        tokens_per_sec = mfu = mbu = None
        if result.decode_s > 0 and result.decode_tokens > 0:
            import jax

            from llm_consensus_tpu.utils.flops import decode_mbu, decode_mfu

            tokens_per_sec = result.decode_tokens / result.decode_s
            n_dev = engine.mesh.devices.size if engine.mesh is not None else 1
            device_kind = jax.devices()[0].device_kind
            mid_context = result.prompt_tokens + len(result.token_ids) // 2
            mfu = decode_mfu(
                engine.cfg,
                tokens_per_sec,
                device_kind,
                n_devices=n_dev,
                context_len=mid_context,
            )
            # Batch-1 decode is HBM-bound, so bandwidth utilization (not
            # MFU) is the number that says how close to the roofline the
            # stream runs; storage widths reflect the engine's quant modes.
            mbu = decode_mbu(
                engine.cfg,
                tokens_per_sec,
                device_kind,
                n_devices=n_dev,
                context_len=mid_context,
                weight_bytes={"int8": 1, "int4": 0.5}.get(engine.quant, 2),
                kv_bytes=1 if engine.kv_quant == "int8" else 2,
            )
        if self._obs is not None:
            # Engine-level trace span: the request trace id's innermost
            # hop (router → gateway → runner → HERE), so one id recovers
            # the on-device half of any slow request's path.
            self._obs.complete(
                "engine_stream", t0_ns, tid="engine", model=req.model,
                trace=req.trace_id, tokens=len(result.token_ids),
            )
        if self._bb is not None:
            self._bb.complete(
                "engine_stream", t0_ns, tid="engine", model=req.model,
                trace=req.trace_id, tokens=len(result.token_ids),
            )
        if self._live is not None and result.token_ids:
            from llm_consensus_tpu.obs.live import class_label

            # Per-token latency histogram, labeled by priority class.
            # Steady-state decode cadence when the engine measured one
            # (decode_s covers tokens after the first chunk); the
            # whole-generation mean as the honest fallback for
            # single-chunk or pooled streams.
            if result.decode_tokens and result.decode_s > 0:
                per_tok = result.decode_s / result.decode_tokens
            else:
                per_tok = (
                    (time.monotonic() - start) / max(1, len(result.token_ids))
                )
            self._live.observe(
                "token_latency", per_tok,
                outcome=(
                    "preempted" if getattr(result, "preempted", False)
                    else "ok"
                ),
                **{"class": class_label(priority)},
            )
        if self._obs is not None and tokens_per_sec is not None:
            # Run-aggregate counters: the CLI footer divides the sums
            # (pool-wide tok/s) and MFU re-weights by tokens, so models
            # of different sizes average honestly. mfu_tokens is the
            # divisor for the MFU mean — only tokens that REPORTED an
            # MFU count, so a chip with no known peak dilutes nothing.
            self._obs.count("decode_tokens", result.decode_tokens)
            self._obs.count("decode_s", result.decode_s)
            if mfu is not None:
                self._obs.count(
                    "mfu_weighted_tokens", mfu * result.decode_tokens
                )
                self._obs.count("mfu_tokens", result.decode_tokens)
        return Response(
            model=req.model,
            content=result.text,
            provider=self.name,
            latency_ms=(time.monotonic() - start) * 1000,
            truncated=result.truncated_prompt,
            tokens=len(result.token_ids),
            tokens_per_sec=tokens_per_sec,
            mfu=mfu,
            mbu=mbu,
            # Speculation telemetry rides the response end to end (the
            # judge records it as last_spec; /statsz and metrics.json
            # aggregate via spec_stats()).
            spec=getattr(result, "spec", None),
            # Per-response KV-reuse degradation (the pool truncated this
            # context's prefix publish) — operators see silent reuse
            # loss at the request, not just in lifetime counters.
            kv=(
                {"truncated": True}
                if getattr(result, "kv_truncated", False) else None
            ),
            preempted=getattr(result, "preempted", False),
        )
