from llm_consensus_tpu.providers.base import (
    Provider,
    ProviderFunc,
    Request,
    Response,
    StreamCallback,
)
from llm_consensus_tpu.providers.registry import Registry, UnknownModelError

__all__ = [
    "Provider",
    "ProviderFunc",
    "Registry",
    "Request",
    "Response",
    "StreamCallback",
    "UnknownModelError",
]
