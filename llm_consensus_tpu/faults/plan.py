"""Deterministic, seeded fault injection.

A :class:`FaultPlan` is built once from a spec string + seed and consulted
at named *sites* threaded through the stack:

  site        kinds              where it fires
  ---------   ----------------   ------------------------------------------
  prefill     prefill_oom        Engine._prefill_ids / _prefill_rows[_suffix]
  decode      decode_fault       Engine.generate_ids / ContinuousBatcher._loop
                                 decode-chunk dispatch
  build       build_fail         TPUProvider._build_engine
  sse         sse_reset          http_sse.post_sse (mid-stream reset)
  runner      worker_stall       Runner worker threads (non-cooperative sleep)
  allgather   controller_drop    multicontroller.allgather_bytes_bounded
              controller_late    (simulated dead / late peer)
  serve       queue_full         serve/admission (forced 429 rejection)
              slow_admit         serve/admission (delayed slot grant; @s=secs)
              disconnect         serve/gateway (client vanishes mid-SSE-stream)
              migrate_stall      serve/gateway migrate loop (phase=migrate:
                                 the destination is slow to accept one
                                 resident stream — @stream=N matches the
                                 Nth resident; the source falls back to
                                 finishing that stream locally, so a
                                 stalled migration degrades to the
                                 drain-and-wait path, never a drop)
  engine      crash              ContinuousBatcher._loop (pool-fatal death
                                 mid-decode — the recovery supervisor's
                                 restart-and-replay trigger)
              wedge              ContinuousBatcher._loop (non-cooperative
                                 stall freezing the decode heartbeat;
                                 @s=secs, default 600)
  router      replica_down       serve/router proxy loop (the replica's
                                 connection dies mid-stream — the fleet
                                 failover trigger; @frame=N matches the
                                 Nth SSE frame of ONE replica attempt —
                                 an attr, so concurrent polls advancing
                                 the site counter can't shift it)
              slow_healthz       serve/fleet health prober (one poll comes
                                 back slow/failed; @s=secs — hysteresis
                                 must absorb it, never flap to dead)
              partition          serve/router proxy connect (the replica
                                 is unreachable before any byte moves)
              replica_flap       serve/elastic controller tick (phase=
                                 elastic: the load signal oscillates for
                                 @s=secs as if a replica were join/leave
                                 flapping — the two-sided scale
                                 hysteresis must absorb it without a
                                 scale decision)
                                 Qualify router specs with @phase=
                                 (connect|proxy|poll|elastic) so one kind
                                 never consumes another phase's fire.
  kv          pool_exhausted     kv/pool.KVPool.publish (the publish grants
                                 no arena slots — the tail past what fit is
                                 truncated; reuse lost, never correctness)
              evict_storm        kv/pool.KVPool.publish (every unreferenced
                                 block evicts before the publish plans —
                                 the radix survives losing its whole
                                 resident set mid-traffic)
  pressure    hbm_squeeze        kv/pool.KVPool.publish (phase=publish:
                                 the effective arena shrinks to @frac=
                                 of its blocks for this publish — the
                                 exhaustion/truncation path fires under
                                 a healthy-sized pool, which is exactly
                                 the signal the pressure governor's
                                 ladder escalates on)
              priority_storm     pressure/governor sample tick
                                 (phase=governor: flood @n= synthetic
                                 LOW-priority admits through the real
                                 admission controller, each holding its
                                 slot @s= seconds — the overload the
                                 ladder must absorb while the HIGH class
                                 keeps completing)
                                 Qualify pressure specs with @phase=
                                 (publish|governor) so one kind never
                                 consumes the other phase's fire.
  spec        acceptance_collapse  speculative round dispatch (engine/
                                 speculative.py + ContinuousBatcher spec
                                 mode): this round's proposals become
                                 junk — greedy output stays exact for
                                 ANY proposals, so acceptance pins to ~1
                                 and the adaptive-k/governor machinery
                                 must absorb a pure SPEED fault
              draft_stall        speculative round dispatch (host
                                 dispatcher sleep; @s=secs, default
                                 0.05 — the governor's A/B must lock
                                 plain rather than ride a stalled
                                 drafter)
  swap        swap_mid_stream    Engine.swap_weights (phase=apply: the
                                 swap request lands while streams hold
                                 pins — forces the pending/double-buffer
                                 path instead of an immediate flip, so
                                 tests exercise pinned residents draining
                                 the old buffer)
              canary_regress     flywheel canary decode dispatch (the
                                 canary-version engine's decode slows by
                                 @s=secs per chunk, default 0.05 — the
                                 latency regression the CanaryWatcher
                                 must catch and auto-roll-back)
              corpus_corrupt     flywheel/corpus extraction (one run
                                 dir's result.json reads as garbage —
                                 the scanner must skip it and count it,
                                 never abort the corpus build)
  disagg      handoff_stall      engine/handoff.KVHandoff worker wave
                                 (@s=secs, default 0.2: the prefill
                                 worker sleeps before its wave, so
                                 waiting submitters hit the bounded-
                                 wait fallback and the handoff queue
                                 backpressures admission)
              prefill_worker_crash  engine/handoff.KVHandoff worker wave
                                 (@wave=N matches the Nth wave: that
                                 wave's prefill dies — its tickets fall
                                 back per-wave to the classic
                                 interleaved-admission path; reuse
                                 lost, never correctness)
  corrupt     bit_flip           integrity verification boundaries
                                 (@surface=kv|wal|ckpt|migration picks
                                 the seam: one bit flips in the
                                 host-visible copy right before its
                                 digest/CRC verify — the plane must
                                 detect it there, contain it, and
                                 repair via recompute/truncate/refuse)
              nan_logits         engine decode-chunk dispatch
                                 (@row=N poisons row N's logits inside
                                 the fused program — the finite-logit
                                 sentinel's per-row verdict must fail
                                 only that stream, with a typed
                                 IntegrityError terminal; neighbors
                                 stay byte-identical)
              torn_wal_tail      recovery/journal WAL close (the last
                                 record's write tears mid-line — the
                                 torn-tail reader must truncate to the
                                 last good record and feed the normal
                                 replay contract)

Spec grammar (``LLMC_FAULTS``)::

    spec   := fault ("," fault)*
    fault  := kind ("@" key "=" value)*

e.g. ``LLMC_FAULTS="prefill_oom@step=3,controller_drop@host=1,sse_reset@chunk=2"``.

Qualifier keys:

  * ``step`` / ``chunk`` — match the site's dispatch counter (1-indexed;
    ``sse_reset@chunk=2`` replaces the 2nd SSE data event with a reset).
  * ``p`` — fire probabilistically; draws come from the plan's seeded RNG,
    so the *sequence* of decisions is a pure function of (seed, spec, call
    order) — same seed ⇒ byte-identical fault sequence.
  * ``times`` — fire at most N times (default 1; ``-1`` = unlimited).
  * any key a site passes as an attribute (``model``, ``preset``) — must
    match exactly.
  * anything else (``host``, ``s``) — a parameter the firing site
    interprets, never a matcher.

Every ``fire()`` appends one line to ``plan.trace`` regardless of outcome,
so two plans driven through the same call sequence are comparable
byte-for-byte via :meth:`FaultPlan.trace_bytes` (asserted in
tests/test_faults.py).

The plan is resolved ONCE per process (faults/__init__.py): consumers bind
``self._faults = faults.plan()`` at construction time, so with
``LLMC_FAULTS`` unset the hot dispatch paths carry a single ``is not None``
check and no injector code runs.
"""

from __future__ import annotations

import random

from llm_consensus_tpu.analysis import sanitizer
import threading
from dataclasses import dataclass, field
from typing import Optional

# site -> kinds that can fire there
SITE_KINDS: dict[str, tuple[str, ...]] = {
    "prefill": ("prefill_oom",),
    "decode": ("decode_fault",),
    "build": ("build_fail",),
    "sse": ("sse_reset",),
    "runner": ("worker_stall",),
    "allgather": ("controller_drop", "controller_late"),
    "serve": ("queue_full", "slow_admit", "disconnect", "migrate_stall"),
    "engine": ("crash", "wedge"),
    "router": ("replica_down", "slow_healthz", "partition", "replica_flap"),
    "kv": ("pool_exhausted", "evict_storm"),
    "spec": ("acceptance_collapse", "draft_stall"),
    "pressure": ("hbm_squeeze", "priority_storm"),
    "disagg": ("handoff_stall", "prefill_worker_crash"),
    "swap": ("swap_mid_stream", "canary_regress", "corpus_corrupt"),
    "corrupt": ("bit_flip", "nan_logits", "torn_wal_tail"),
}

KNOWN_KINDS = frozenset(k for kinds in SITE_KINDS.values() for k in kinds)

# Keys that participate in matching even though sites never pass them as
# attributes. Everything else unknown is a parameter for the firing site.
_COUNTER_KEYS = ("step", "chunk")


class InjectedFault(RuntimeError):
    """A fault fired by the injection plan (never raised in production:
    constructing a FaultPlan requires LLMC_FAULTS / an explicit install)."""


@dataclass
class FaultSpec:
    """One parsed fault from the spec string."""

    kind: str
    args: dict = field(default_factory=dict)
    times: int = 1  # remaining fires; -1 = unlimited

    def param(self, key: str, default=None):
        return self.args.get(key, default)


def parse_spec(spec: str) -> list[FaultSpec]:
    """Parse ``LLMC_FAULTS`` grammar into FaultSpecs (order-preserving)."""
    out: list[FaultSpec] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split("@")
        kind = fields[0].strip()
        if kind not in KNOWN_KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in LLMC_FAULTS "
                f"(known: {sorted(KNOWN_KINDS)})"
            )
        args: dict = {}
        for f in fields[1:]:
            f = f.strip()
            if not f:
                continue
            if "=" not in f:
                raise ValueError(
                    f"malformed fault qualifier {f!r} in {part!r} "
                    "(expected key=value)"
                )
            key, _, value = f.partition("=")
            args[key.strip()] = value.strip()
        times = int(args.pop("times", 1))
        out.append(FaultSpec(kind=kind, args=args, times=times))
    return out


class FaultPlan:
    """Seeded, thread-safe fault schedule over named sites.

    ``fire(site, **attrs)`` advances the site's counter, decides whether any
    spec fires, records the decision in ``trace``, and returns the fired
    spec (or None). ``check(site, **attrs)`` is the raising form for sites
    whose faults model a device/runtime error.
    """

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = seed
        self._specs = parse_spec(spec)
        self._rng = random.Random(seed)
        self._counts: dict[str, int] = {}
        self._lock = sanitizer.make_lock("faults.plan")
        self.trace: list[str] = []

    def _matches(self, fs: FaultSpec, n: int, attrs: dict) -> bool:
        p: Optional[float] = None
        for key, value in fs.args.items():
            if key == "p":
                p = float(value)  # drawn LAST, below — see comment
            elif key in _COUNTER_KEYS:
                if int(value) != n:
                    return False
            elif key in attrs:
                if str(attrs[key]) != str(value):
                    return False
            # else: a site parameter (host=, s=, ...) — never a matcher.
        if p is not None:
            # The draw happens only after every OTHER qualifier matched —
            # regardless of where p= sits in the spec string — so the RNG
            # stream consumed is a function of the matching call sequence
            # alone, and qualifier ordering cannot shift later
            # probabilistic decisions.
            return self._rng.random() < p
        return True

    def fire(self, site: str, **attrs) -> Optional[FaultSpec]:
        with self._lock:
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
            hit: Optional[FaultSpec] = None
            for fs in self._specs:
                if fs.kind not in SITE_KINDS.get(site, ()):
                    continue
                if fs.times == 0:
                    continue
                if not self._matches(fs, n, attrs):
                    continue
                if fs.times > 0:
                    fs.times -= 1
                hit = fs
                break
            tags = ",".join(f"{k}={attrs[k]}" for k in sorted(attrs))
            self.trace.append(
                f"{site}#{n}[{tags}]->{hit.kind if hit else '-'}"
            )
        if hit is not None:
            # Every injected fault lands on the run timeline (obs/; no-op
            # when telemetry is off). Resolved per fire, outside the plan
            # lock: faults only ever fire under chaos, never on a clean
            # run's hot path, and tests install recorder and plan in
            # either order.
            from llm_consensus_tpu import obs

            r = obs.recorder()
            if r is not None:
                r.instant(
                    f"fault:{hit.kind}", tid="faults", site=site, n=n,
                    **{k: str(v) for k, v in attrs.items()},
                )
        return hit

    def check(self, site: str, **attrs) -> None:
        """Raise :class:`InjectedFault` when a fault fires at ``site``."""
        fs = self.fire(site, **attrs)
        if fs is not None:
            raise InjectedFault(
                f"injected {fs.kind} at site {site!r} "
                f"(spec {self.spec!r}, seed {self.seed})"
            )

    def trace_bytes(self) -> bytes:
        """The decision sequence, serialized — byte-identical for two plans
        with the same (seed, spec) driven through the same call sequence."""
        with self._lock:
            return ("\n".join(self.trace) + "\n").encode("utf-8")
