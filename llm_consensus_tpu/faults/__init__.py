"""Fault-injection entry point: the process-wide plan.

``plan()`` resolves LLMC_FAULTS / LLMC_FAULTS_SEED exactly once and caches
the result (None when unset). Consumers bind the plan at construction time
(``self._faults = faults.plan()``) so disabled runs pay a single attribute
None-check on the hot dispatch paths — the injection decision is made at
plan-construction time, never per-dispatch.

``install()`` / ``reset()`` exist for tests and the chaos dryrun lane,
which flip plans mid-process; production only ever resolves from the
environment.
"""

from __future__ import annotations

import threading
from typing import Optional

from llm_consensus_tpu.analysis import sanitizer
from llm_consensus_tpu.faults.plan import (  # noqa: F401 — public API
    SITE_KINDS, FaultPlan, FaultSpec, InjectedFault, parse_spec)
from llm_consensus_tpu.utils import knobs

__all__ = [
    "SITE_KINDS", "FaultPlan", "FaultSpec", "InjectedFault",
    "parse_spec", "plan", "install", "reset",
]

_lock = sanitizer.make_lock("faults.registry")
_plan: Optional[FaultPlan] = None
_resolved = False


def plan() -> Optional[FaultPlan]:
    """The process-wide fault plan, or None when injection is disabled."""
    global _plan, _resolved
    if not _resolved:
        with _lock:
            if not _resolved:
                spec = knobs.get_str("LLMC_FAULTS")
                if spec:
                    seed = knobs.get_int("LLMC_FAULTS_SEED")
                    _plan = FaultPlan(spec, seed=seed)
                _resolved = True
    return _plan


def install(p: Optional[FaultPlan]) -> None:
    """Install ``p`` as the process plan (tests / chaos dryrun)."""
    global _plan, _resolved
    with _lock:
        _plan = p
        _resolved = True


def reset() -> None:
    """Forget the cached plan; the next ``plan()`` re-reads the env."""
    global _plan, _resolved
    with _lock:
        _plan = None
        _resolved = False
