"""Version metadata.

Parity: the reference injects version/commit/date via goreleaser ldflags
(/root/reference/cmd/llm-consensus/main.go:26-31, .goreleaser.yaml:26-30).
Here the same three fields are module attributes, overridable at build or
install time by writing _build_info.py next to this file.
"""

__version__ = "0.1.0"
__commit__ = "none"
__date__ = "unknown"

try:  # populated by packaging, absent in a source checkout
    from llm_consensus_tpu._build_info import __commit__, __date__, __version__  # noqa: F401
except ImportError:
    pass


def version_string(prog: str = "llm-consensus") -> str:
    """Multi-line version banner (format parity: main.go:325-330)."""
    return f"{prog} {__version__}\n  commit: {__commit__}\n  built:  {__date__}"
