"""``python -m llm_consensus_tpu`` — the llm-consensus CLI."""

import sys

from llm_consensus_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
