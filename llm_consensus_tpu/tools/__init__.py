"""Standalone maintenance tools shipped alongside the CLI.

Parity: the reference ships a second binary, ``cmd/model-registry-sync``
(/root/reference/cmd/model-registry-sync/main.go) — a model-catalog fetcher
that is built and released independently of the main CLI. Here the tools
live as runnable modules (``python -m llm_consensus_tpu.tools.registry_sync``)
and as console scripts via packaging metadata.
"""
