"""model-registry-sync — build a normalized model catalog from many sources.

Parity: /root/reference/cmd/model-registry-sync/main.go. The reference is a
standalone binary that fetches the OpenAI model list (``GET /v1/models``,
main.go:136-140) and the OpenRouter list (``GET /api/v1/models``,
main.go:173-182), normalizes both into ``ModelRecord{Source, ID, Name,
ContextLength, Pricing, Raw}`` (main.go:18-25), stable-sorts by
``(source, id)`` (main.go:100-105), and writes JSON to stdout or ``--out``
(main.go:112-119). A source failing is non-fatal: the records from healthy
sources are still written and the failures are warned at the end
(main.go:121-127).

New in the TPU build: a ``local`` source that enumerates the framework's
on-device model catalog (models/config.py presets) — the models this
framework can actually run without any network — with ``context_length``
taken from the preset's ``max_seq_len`` and parameter counts in ``raw``.
The remote sources remain useful for the HTTP provider path (BASELINE
config[0]) and keep the reference's catalog format alive.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Optional

DEFAULT_OPENAI_BASE = "https://api.openai.com/v1"
DEFAULT_OPENROUTER_BASE = "https://openrouter.ai/api/v1"
DEFAULT_TIMEOUT_S = 30.0


@dataclass
class ModelRecord:
    """One catalog entry, normalized across sources.

    Field set parity: model-registry-sync/main.go:18-25 (Source, ID, Name,
    ContextLength, Pricing, Raw).
    """

    source: str
    id: str
    name: str = ""
    context_length: Optional[int] = None
    pricing: Optional[dict] = None
    raw: Optional[dict] = field(default=None, repr=False)

    def to_json(self, include_raw: bool) -> dict:
        out: dict = {"source": self.source, "id": self.id}
        if self.name:
            out["name"] = self.name
        if self.context_length is not None:
            out["context_length"] = self.context_length
        if self.pricing is not None:
            out["pricing"] = self.pricing
        if include_raw and self.raw is not None:
            out["raw"] = self.raw
        return out


class SourceError(RuntimeError):
    """A catalog source failed entirely (network, auth, bad payload)."""


def _http_get_json(url: str, headers: dict[str, str], timeout: float) -> dict:
    req = urllib.request.Request(url, headers=headers, method="GET")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read()
    except urllib.error.HTTPError as e:
        detail = e.read()[:500].decode("utf-8", "replace")
        raise SourceError(f"GET {url}: status {e.code}: {detail}") from e
    except (urllib.error.URLError, OSError) as e:
        raise SourceError(f"GET {url}: {e}") from e
    try:
        payload = json.loads(body)
    except json.JSONDecodeError as e:
        raise SourceError(f"GET {url}: invalid JSON: {e}") from e
    if not isinstance(payload, dict):
        raise SourceError(f"GET {url}: expected JSON object, got {type(payload).__name__}")
    return payload


def _data_items(payload: dict, url: str) -> list[dict]:
    """The ``data`` array of a catalog payload, dict entries only.

    Feeds occasionally ship junk entries; non-dict items are dropped rather
    than crashing so one odd record can't take the whole source down."""
    data = payload.get("data", [])
    if not isinstance(data, list):
        raise SourceError(f"{url}: 'data' is not a list")
    return [item for item in data if isinstance(item, dict)]


def _clean_str(value) -> str:
    """Feed string field → str; null/non-string junk → "" (record dropped
    or field blanked, never the literal "None")."""
    return value if isinstance(value, str) else ""


def _clean_int(value) -> Optional[int]:
    """Feed numeric field → int, or None for junk — including the
    ``Infinity``/``NaN`` literals Python's json parser accepts, which would
    otherwise raise past the per-source error isolation in sync()."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    if isinstance(value, float) and not math.isfinite(value):
        return None
    return int(value)


def fetch_openai_models(
    base_url: str = DEFAULT_OPENAI_BASE,
    api_key: Optional[str] = None,
    timeout: float = DEFAULT_TIMEOUT_S,
) -> list[ModelRecord]:
    """OpenAI ``GET {base}/models`` → records. Requires an API key
    (env ``OPENAI_API_KEY`` unless passed), as main.go:130-140."""
    key = api_key or os.environ.get("OPENAI_API_KEY", "")
    if not key:
        raise SourceError("openai: OPENAI_API_KEY not set")
    payload = _http_get_json(
        f"{base_url.rstrip('/')}/models",
        {"Authorization": f"Bearer {key}"},
        timeout,
    )
    records = []
    for item in _data_items(payload, url="openai"):
        mid = _clean_str(item.get("id"))
        if not mid:
            continue
        records.append(ModelRecord(source="openai", id=mid, raw=item))
    return records


def fetch_openrouter_models(
    base_url: str = DEFAULT_OPENROUTER_BASE,
    api_key: Optional[str] = None,
    timeout: float = DEFAULT_TIMEOUT_S,
) -> list[ModelRecord]:
    """OpenRouter ``GET {base}/models`` → records with context_length and
    per-token pricing (main.go:172-216). The key is optional."""
    key = api_key or os.environ.get("OPENROUTER_API_KEY", "")
    headers = {"Authorization": f"Bearer {key}"} if key else {}
    payload = _http_get_json(f"{base_url.rstrip('/')}/models", headers, timeout)
    records = []
    for item in _data_items(payload, url="openrouter"):
        mid = _clean_str(item.get("id"))
        if not mid:
            continue
        ctx = item.get("context_length")
        pricing = item.get("pricing")
        records.append(
            ModelRecord(
                source="openrouter",
                id=mid,
                name=_clean_str(item.get("name")),
                context_length=_clean_int(ctx),
                pricing={k: str(v) for k, v in pricing.items()}
                if isinstance(pricing, dict)
                else None,
                raw=item,
            )
        )
    return records


def fetch_local_models() -> list[ModelRecord]:
    """The on-device catalog: every model preset this framework can run.

    No network involved — this is the source of truth for ``tpu:<model>``
    names the CLI accepts, the TPU-native analog of the remote catalogs.
    """
    from llm_consensus_tpu.models import MODEL_PRESETS

    records = []
    for name, cfg in MODEL_PRESETS.items():
        records.append(
            ModelRecord(
                source="local",
                id=f"tpu:{name}",
                name=name,
                context_length=cfg.max_seq_len,
                raw={
                    "family": cfg.family,
                    "n_params": cfg.n_params(),
                    "n_layers": cfg.n_layers,
                    "d_model": cfg.d_model,
                    "moe": cfg.is_moe,
                },
            )
        )
    return records


def sync(
    sources: dict[str, Callable[[], list[ModelRecord]]],
) -> tuple[list[ModelRecord], list[str]]:
    """Run every enabled source; collect records and per-source warnings.

    Partial failure is non-fatal (main.go:121-127): a failing source adds a
    warning and the rest proceed. Output is stable-sorted by (source, id)
    (main.go:100-105).
    """
    records: list[ModelRecord] = []
    warnings: list[str] = []
    for name, fetch in sources.items():
        try:
            records.extend(fetch())
        except SourceError as e:
            warnings.append(f"{name}: {e}")
    records.sort(key=lambda r: (r.source, r.id))
    return records, warnings


def render(records: list[ModelRecord], include_raw: bool) -> str:
    return json.dumps(
        [r.to_json(include_raw) for r in records], indent=2, ensure_ascii=False
    )


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="model-registry-sync",
        description="Fetch model catalogs and write a normalized JSON registry.",
    )
    p.add_argument("--out", default="", help="output path (default: stdout)")
    p.add_argument(
        "--raw", action="store_true", help="include each source's raw payload"
    )
    p.add_argument(
        "--openai",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="include the OpenAI source (needs OPENAI_API_KEY)",
    )
    p.add_argument(
        "--openrouter",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="include the OpenRouter source",
    )
    p.add_argument(
        "--local",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="include the on-device model catalog",
    )
    p.add_argument(
        "--timeout", type=float, default=DEFAULT_TIMEOUT_S, help="per-request timeout (s)"
    )
    p.add_argument("--openai-base-url", default=DEFAULT_OPENAI_BASE, help=argparse.SUPPRESS)
    p.add_argument(
        "--openrouter-base-url", default=DEFAULT_OPENROUTER_BASE, help=argparse.SUPPRESS
    )
    args = p.parse_args(argv)

    sources: dict[str, Callable[[], list[ModelRecord]]] = {}
    if args.local:
        sources["local"] = fetch_local_models
    if args.openai:
        sources["openai"] = lambda: fetch_openai_models(
            base_url=args.openai_base_url, timeout=args.timeout
        )
    if args.openrouter:
        sources["openrouter"] = lambda: fetch_openrouter_models(
            base_url=args.openrouter_base_url, timeout=args.timeout
        )
    if not sources:
        print("error: no sources enabled", file=sys.stderr)
        return 1

    records, warnings = sync(sources)
    text = render(records, include_raw=args.raw)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    else:
        print(text)

    for w in warnings:
        print(f"warning: {w}", file=sys.stderr)
    # All sources down and nothing to show → hard failure; any healthy
    # source keeps the exit clean (reference: warn-and-continue).
    if not records and warnings:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
