from llm_consensus_tpu.output.result import Result

__all__ = ["Result"]
