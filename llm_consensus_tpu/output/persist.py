"""Run persistence: every run auto-saved to data/<run-id>/.

Parity: /root/reference/cmd/llm-consensus/main.go:191-216 (layout) and
:278-285 (run-id format: timestamp + 3 random bytes hex, e.g.
``20260112-143052-a1b2c3``).
"""

from __future__ import annotations

import os
import secrets
import time
from typing import Callable, Optional


def generate_run_id(now: float | None = None) -> str:
    ts = time.strftime("%Y%m%d-%H%M%S", time.localtime(now))
    return f"{ts}-{secrets.token_hex(3)}"


def save_file(
    run_dir: str,
    name: str,
    content: "str | bytes",
    warn: Optional[Callable[[str], None]] = None,
) -> Optional[str]:
    """Write one aux file into ``run_dir`` (created if needed).

    Non-fatal like the reference's aux writes (main.go:203-216): a failure
    is reported via ``warn`` and returns None — telemetry and fault traces
    must never fail a run that already produced its answer. Returns the
    written path on success.
    """
    path = os.path.join(run_dir, name)
    try:
        os.makedirs(run_dir, exist_ok=True)
        mode = "wb" if isinstance(content, bytes) else "w"
        kwargs = {} if isinstance(content, bytes) else {"encoding": "utf-8"}
        with open(path, mode, **kwargs) as f:
            f.write(content)
    except OSError as err:
        if warn is not None:
            warn(f"Failed to save {name.split('.')[0]}: {err}")
        return None
    return path


def save_aux_files(
    run_dir: str,
    prompt: str,
    consensus: str,
    warn: Optional[Callable[[str], None]] = None,
) -> str:
    """Create ``run_dir`` and write prompt.txt / consensus.md into it.

    Write failures of the aux files are non-fatal, reported via ``warn``
    (main.go:203-216). result.json is written by the caller through the
    common output-path branch, as in the reference. Returns the result.json
    path for that branch.
    """
    os.makedirs(run_dir, exist_ok=True)
    for name, content in (("prompt.txt", prompt), ("consensus.md", consensus)):
        save_file(run_dir, name, content, warn=warn)
    return os.path.join(run_dir, "result.json")
