"""Run persistence: every run auto-saved to data/<run-id>/.

Parity: /root/reference/cmd/llm-consensus/main.go:191-216 (layout) and
:278-285 (run-id format: timestamp + 3 random bytes hex, e.g.
``20260112-143052-a1b2c3``).
"""

from __future__ import annotations

import os
import secrets
import threading
import time

from llm_consensus_tpu.analysis import sanitizer
from typing import Callable, Optional

# In-process collision guard for generate_run_id: the id format is
# wall-clock-derived down to the second, so a burst of concurrent server
# runs can draw the same timestamp — and 3 random bytes alone leave a
# birthday collision on the table. Remembering the ids issued within the
# CURRENT second (the set resets when the second rolls over, so memory
# stays bounded on a long-lived server) makes two calls from one process
# provably never collide, while keeping the reference's id format intact.
_id_lock = sanitizer.make_lock("output.runid")
_id_second = ""
_id_issued: set = set()


def generate_run_id(now: float | None = None) -> str:
    global _id_second
    ts = time.strftime("%Y%m%d-%H%M%S", time.localtime(now))
    with _id_lock:
        if ts != _id_second:
            _id_second = ts
            _id_issued.clear()
        while True:
            run_id = f"{ts}-{secrets.token_hex(3)}"
            if run_id not in _id_issued:
                _id_issued.add(run_id)
                return run_id


def reserve_run_dir(
    data_dir: str, now: float | None = None, attempts: int = 64
) -> tuple[str, str]:
    """Atomically claim a fresh ``data/<run-id>/``; returns (run_id, path).

    The authoritative cross-process guard: the exclusive ``mkdir`` is the
    reservation, and an id another process (or an earlier crash) already
    claimed is simply redrawn — retry-on-exists, as many times as it
    takes (bounded only to turn a pathological filesystem into an error
    instead of a spin).
    """
    last_err: Optional[OSError] = None
    for _ in range(attempts):
        run_id = generate_run_id(now)
        path = os.path.join(data_dir, run_id)
        try:
            os.makedirs(path, exist_ok=False)
        except FileExistsError as err:
            last_err = err
            continue
        return run_id, path
    raise OSError(
        f"could not reserve a unique run dir under {data_dir!r} "
        f"after {attempts} attempts"
    ) from last_err


def save_file(
    run_dir: str,
    name: str,
    content: "str | bytes",
    warn: Optional[Callable[[str], None]] = None,
) -> Optional[str]:
    """Crash-safely write one aux file into ``run_dir`` (created if
    needed): write to a temp file in the SAME directory, fsync, then
    ``os.replace`` into place, then fsync the DIRECTORY — the rename
    alone is atomic but not durable, and a power cut after return must
    not roll the directory entry back to nothing. A crash mid-write
    leaves either the old file or the new one, never a torn
    ``trace.json``/``metrics.json`` (the resume path reads these dirs
    back, so torn JSON is not merely cosmetic).

    Non-fatal like the reference's aux writes (main.go:203-216): a failure
    is reported via ``warn`` and returns None — telemetry and fault traces
    must never fail a run that already produced its answer. Returns the
    written path on success.
    """
    path = os.path.join(run_dir, name)
    tmp = None
    try:
        os.makedirs(run_dir, exist_ok=True)
        data = content if isinstance(content, bytes) else content.encode("utf-8")
        import tempfile

        fd, tmp = tempfile.mkstemp(
            dir=run_dir, prefix=f".{os.path.basename(name)}.", suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            tmp = None
            _fsync_dir(run_dir)
        finally:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
    except OSError as err:
        if warn is not None:
            warn(f"Failed to save {name.split('.')[0]}: {err}")
        return None
    return path


def _fsync_dir(path: str) -> None:
    """Best-effort directory fsync: makes a just-renamed entry durable
    (the file fsync above only hardened its bytes, not the name)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory-open semantics
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save_aux_files(
    run_dir: str,
    prompt: str,
    consensus: str,
    warn: Optional[Callable[[str], None]] = None,
) -> str:
    """Create ``run_dir`` and write prompt.txt / consensus.md into it.

    Write failures of the aux files are non-fatal, reported via ``warn``
    (main.go:203-216). result.json is written by the caller through the
    common output-path branch, as in the reference. Returns the result.json
    path for that branch.
    """
    os.makedirs(run_dir, exist_ok=True)
    for name, content in (("prompt.txt", prompt), ("consensus.md", consensus)):
        save_file(run_dir, name, content, warn=warn)
    return os.path.join(run_dir, "result.json")
