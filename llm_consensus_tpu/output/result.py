"""The stable JSON output contract of a consensus run.

Parity: /root/reference/internal/output/output.go:8-15 — field order and
names match the reference's JSON tags, with ``warnings`` and
``failed_models`` omitted when empty (omitempty).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from llm_consensus_tpu.providers import Response


@dataclass
class Result:
    prompt: str
    responses: list[Response]
    consensus: str
    judge: str
    warnings: list[str] = field(default_factory=list)
    failed_models: list[str] = field(default_factory=list)
    # Conversation history for --continue (TPU-build extension, reference
    # roadmap §3.1): earlier {prompt, consensus} exchanges, oldest first.
    # Omitted when empty so the reference JSON shape is unchanged.
    history: list[dict] = field(default_factory=list)
    # Panel agreement analysis (roadmap §2.4): {score, level, divergence}.
    agreement: "dict | None" = None
    # LLM-graded confidence in the consensus (roadmap §2.4, --confidence):
    # {score: 0-100 | null, controversy: [str]}.
    confidence: "dict | None" = None

    def to_dict(self) -> dict:
        out = {
            "prompt": self.prompt,
            "responses": [r.to_dict() for r in self.responses],
            "consensus": self.consensus,
            "judge": self.judge,
        }
        if self.warnings:
            out["warnings"] = self.warnings
        if self.failed_models:
            out["failed_models"] = self.failed_models
        if self.history:
            out["history"] = self.history
        if self.agreement is not None:
            out["agreement"] = self.agreement
        if self.confidence is not None:
            out["confidence"] = self.confidence
        return out

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, ensure_ascii=False) + "\n"
